"""Scheduling queue: the reference's 3-queue PriorityQueue design.

reference: pkg/scheduler/internal/queue/scheduling_queue.go —
PriorityQueue :113 with
  activeQ        heap of pods ready to schedule (QueueSort less-func)
  podBackoffQ    heap ordered by backoff expiry (:131-135)
  unschedulableQ map of pods waiting for a cluster event (:46-48)
plus the PodNominator (nominated pods per node, framework/v1alpha1
interface.go:537) which this class embeds like the reference does.

Flow mirrors the reference exactly:
  Pop :378 blocks until activeQ non-empty; increments schedulingCycle.
  AddUnschedulableIfNotPresent :297 routes a failed pod to backoffQ when a
    move request arrived during its scheduling cycle, else unschedulableQ.
  MoveAllToActiveOrBackoffQueue :500 (cluster event) moves unschedulable
    pods to backoffQ (still backing off) or activeQ, bumps moveRequestCycle.
  flush_backoff_completed :241-243 (1 s period) promotes expired backoff.
  flush_unschedulable_leftover (30 s period) moves pods stuck > 60 s.
Backoff is exponential per attempt: 1 s * 2^attempts capped at 10 s
(reference: scheduler.go:205-206 podInitialBackoff/podMaxBackoff,
scheduling_queue.go:803 calculateBackoffDuration).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..api import types as api
from ..framework.types import QueuedPodInfo, pod_with_affinity
from ..utils import slo as uslo
from ..utils.trace import wallclock
from .heap import Heap

DEFAULT_POD_INITIAL_BACKOFF = 1.0   # reference: scheduler.go:205
DEFAULT_POD_MAX_BACKOFF = 10.0      # reference: scheduler.go:206
UNSCHEDULABLE_TIMEOUT = 60.0        # reference: scheduling_queue.go:48
BACKOFF_FLUSH_PERIOD = 1.0          # reference: scheduling_queue.go:243
UNSCHEDULABLE_FLUSH_PERIOD = 30.0   # reference: scheduling_queue.go:46


def default_sort_key(qp: QueuedPodInfo):
    """PrioritySort order: higher priority first, FIFO tie-break on the
    queue timestamp (reference: queuesort/priority_sort.go:40-45).  Sort
    keys are snapshotted at push time (see heap.py) so in-place
    QueuedPodInfo mutation cannot corrupt the heap."""
    return (-qp.pod.priority(), qp.timestamp)


def _pod_key(pod: api.Pod) -> str:
    return f"{pod.namespace}/{pod.metadata.name}"


class PodNominator:
    """Tracks pods nominated to nodes by preemption
    (reference: framework/v1alpha1/interface.go:537 PodNominator,
    scheduling_queue.go:737 nominatedPodMap)."""

    def __init__(self):
        self._nominated: Dict[str, List[api.Pod]] = {}  # kubelint: guarded-by(_lock)
        self._nominated_pod_to_node: Dict[str, str] = {}  # kubelint: guarded-by(_lock)
        self._lock = threading.Lock()

    def add_nominated_pod(self, pod: api.Pod, node_name: str) -> None:
        with self._lock:
            self._add(pod, node_name)

    def _add(self, pod: api.Pod, node_name: str) -> None:
        # always delete first (reference: scheduling_queue.go:756)
        self._delete(pod)
        nn = node_name or pod.status.nominated_node_name
        if not nn:
            return
        self._nominated_pod_to_node[pod.uid] = nn
        lst = self._nominated.setdefault(nn, [])
        if not any(p.uid == pod.uid for p in lst):
            lst.append(pod)

    def delete_nominated_pod_if_exists(self, pod: api.Pod) -> None:
        with self._lock:
            self._delete(pod)

    def _delete(self, pod: api.Pod) -> None:
        nn = self._nominated_pod_to_node.pop(pod.uid, None)
        if nn is None:
            return
        lst = self._nominated.get(nn, [])
        self._nominated[nn] = [p for p in lst if p.uid != pod.uid]
        if not self._nominated[nn]:
            del self._nominated[nn]

    def update_nominated_pod(self, old: api.Pod, new: api.Pod) -> None:
        with self._lock:
            # preserve nomination during update (reference: :774)
            node = self._nominated_pod_to_node.get(old.uid, "")
            self._delete(old)
            self._add(new, node)

    def nominated_pods_for_node(self, node_name: str) -> List[api.Pod]:
        with self._lock:
            return list(self._nominated.get(node_name, []))

    def all_nominated(self) -> List[Tuple[api.Pod, str]]:
        """Every (pod, nominated node) pair.  The reference iterates
        NominatedPodsForNode per candidate node inside addNominatedPods
        (generic_scheduler.go:530); the batched overlay wants them all at
        once."""
        with self._lock:
            return [(p, nn) for nn, pods in self._nominated.items()
                    for p in pods]


class SchedulingQueue(PodNominator):
    """reference: scheduling_queue.go:113 PriorityQueue."""

    def __init__(self,
                 sort_key: Callable[[QueuedPodInfo], tuple] = default_sort_key,
                 pod_initial_backoff: float = DEFAULT_POD_INITIAL_BACKOFF,
                 pod_max_backoff: float = DEFAULT_POD_MAX_BACKOFF,
                 # wallclock, not time.time: every queue stamp is one
                 # end of an SLO/backoff DURATION (queue_wait, backoff,
                 # cycle_wait, e2e) whose other end is a scheduler-side
                 # wallclock stamp — an NTP step must not corrupt them.
                 # Tests can still inject a fake clock.
                 clock: Callable[[], float] = wallclock,
                 metrics=None):
        super().__init__()
        self._clock = clock
        self._initial_backoff = pod_initial_backoff
        self._max_backoff = pod_max_backoff
        self._cond = threading.Condition()
        self._closed = False
        key = lambda qp: _pod_key(qp.pod)
        m = metrics
        self.active_q = Heap(key, sort_key,  # kubelint: guarded-by(_cond)
                             m.active_recorder() if m else None)
        self.backoff_q = Heap(key, self._backoff_time,  # kubelint: guarded-by(_cond)
                              m.backoff_recorder() if m else None)
        self.unschedulable_q: Dict[str, QueuedPodInfo] = {}  # kubelint: guarded-by(_cond)
        self._unschedulable_recorder = m.unschedulable_recorder() if m else None
        self._metrics = metrics
        self.scheduling_cycle = 0           # reference: :120
        self.move_request_cycle = -1        # reference: :125
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- backoff ------------------------------------------------------------

    def _backoff_time(self, qp: QueuedPodInfo) -> float:
        """reference: scheduling_queue.go:795 getBackoffTime /
        :803 calculateBackoffDuration."""
        d = self._initial_backoff
        for _ in range(qp.attempts - 1):
            d *= 2
            if d >= self._max_backoff:
                return qp.timestamp + self._max_backoff
        return qp.timestamp + min(d, self._max_backoff)

    def _is_backing_off(self, qp: QueuedPodInfo) -> bool:
        return self._backoff_time(qp) > self._clock()

    # -- core ops -----------------------------------------------------------

    def add(self, pod: api.Pod) -> None:
        """New pending pod -> activeQ (reference: :270 Add)."""
        with self._cond:
            qp = self._new_queued_pod_info(pod)
            self.active_q.add(qp)
            self.backoff_q.delete(qp)
            self.unschedulable_q.pop(_pod_key(pod), None)
            # via the public wrapper: the nominator maps are _lock-guarded
            # and preemption threads mutate them concurrently — the old
            # direct self._add() bypassed _lock (caught by
            # concurrency/unguarded-access)
            self.add_nominated_pod(pod, "")
            if self._metrics:
                self._metrics.incoming("PodAdd", "active")
            self._cond.notify()

    def _new_queued_pod_info(self, pod: api.Pod) -> QueuedPodInfo:
        now = self._clock()
        return QueuedPodInfo(pod=pod, timestamp=now,
                             initial_attempt_timestamp=now)

    def add_unschedulable_if_not_present(self, qp: QueuedPodInfo,
                                         pod_scheduling_cycle: int) -> None:
        """Failed pod back into the queue (reference: :297)."""
        with self._cond:
            k = _pod_key(qp.pod)
            if k in self.unschedulable_q:
                raise ValueError(f"pod {k} already in unschedulableQ")
            if self.active_q.get(qp) is not None:
                raise ValueError(f"pod {k} already in activeQ")
            if self.backoff_q.get(qp) is not None:
                raise ValueError(f"pod {k} already in backoffQ")
            qp.timestamp = self._clock()
            # a move request happened while this pod was being scheduled:
            # skip unschedulableQ so the new cluster state is retried
            # promptly (reference: :316-326)
            if self.move_request_cycle >= pod_scheduling_cycle:
                self.backoff_q.add(qp)
                if self._metrics:
                    self._metrics.incoming("ScheduleAttemptFailure", "backoff")
            else:
                self.unschedulable_q[k] = qp
                if self._unschedulable_recorder:
                    self._unschedulable_recorder.inc()
                if self._metrics:
                    self._metrics.incoming("ScheduleAttemptFailure",
                                           "unschedulable")
            self.add_nominated_pod(qp.pod, "")
            self._cond.notify()

    def pop(self, timeout: Optional[float] = None) -> Optional[QueuedPodInfo]:
        """Blocks until a pod is available (reference: :378)."""
        with self._cond:
            while len(self.active_q) == 0 and not self._closed:
                if not self._cond.wait(timeout=timeout):
                    return None
            if self._closed and len(self.active_q) == 0:
                return None
            qp = self.active_q.pop()
            qp.attempts += 1
            self.scheduling_cycle += 1
            qp.scheduling_cycle = self.scheduling_cycle
            if uslo.tracker() is not None:
                # SLO queue_wait boundary; disarmed this is one module
                # attribute read — no clock call, no lock
                qp.pop_timestamp = self._clock()
            return qp

    def pop_batch(self, max_batch: int,
                  timeout: Optional[float] = None) -> List[QueuedPodInfo]:
        """TPU extension: drain up to max_batch ready pods in queue order for
        one device batch (the reference pops strictly one, scheduler.go:510;
        batching is our throughput lever — SURVEY.md §7).

        When a BLOCKING pop wakes on the first pod of an arriving burst, a
        short gather window lets the rest of the burst land before the
        drain: waking instantly mid-burst splits one arrival wave into
        arbitrary-sized cycles, which costs an extra serialized device
        cycle AND churns the pow2 pod-axis bucket (a 196/60 split compiles
        two programs where a 256-pod cycle reuses one).  Non-blocking pops
        (timeout == 0) never wait — test/drain semantics are unchanged."""
        out: List[QueuedPodInfo] = []
        first = self.pop(timeout=timeout)
        if first is None:
            return out
        out.append(first)
        if (timeout is None or timeout > 0) and len(out) < max_batch:
            gather = 0.02 if timeout is None else min(0.02, timeout)
            with self._cond:
                # one cond wait instead of a 2 ms poll loop: wakes on the
                # notify that completes the batch, or at the window's end
                self._cond.wait_for(
                    lambda: len(self.active_q) >= max_batch - len(out),
                    timeout=gather)
        with self._cond:
            # one clock read for the whole drained batch (SLO armed only)
            pop_t = self._clock() if uslo.tracker() is not None else 0.0
            while len(out) < max_batch and len(self.active_q) > 0:
                qp = self.active_q.pop()
                qp.attempts += 1
                self.scheduling_cycle += 1
                qp.scheduling_cycle = self.scheduling_cycle
                if pop_t:
                    qp.pop_timestamp = pop_t
                out.append(qp)
        return out

    def update(self, old: Optional[api.Pod], new: api.Pod) -> None:
        """reference: :404 Update — refresh in place; an updated
        unschedulable pod that might now fit moves to active/backoff."""
        with self._cond:
            if old is not None:
                qp = self.active_q.get_by_key(_pod_key(old))
                if qp is not None:
                    self.update_nominated_pod(old, new)
                    qp.pod = new
                    self.active_q.add(qp)
                    self._cond.notify()
                    return
                qp = self.backoff_q.get_by_key(_pod_key(old))
                if qp is not None:
                    self.update_nominated_pod(old, new)
                    qp.pod = new
                    self.backoff_q.add(qp)
                    return
            k = _pod_key(new)
            qp = self.unschedulable_q.get(k)
            if qp is not None:
                self.update_nominated_pod(qp.pod, new)
                if _pod_updates_may_make_schedulable(qp.pod, new):
                    del self.unschedulable_q[k]
                    if self._unschedulable_recorder:
                        self._unschedulable_recorder.dec()
                    qp.pod = new
                    if self._is_backing_off(qp):
                        self.backoff_q.add(qp)
                    else:
                        self.active_q.add(qp)
                        self._cond.notify()
                else:
                    qp.pod = new
                return
            # unknown pod: treat as new
            self.active_q.add(self._new_queued_pod_info(new))
            self.add_nominated_pod(new, "")
            self._cond.notify()

    def delete(self, pod: api.Pod) -> None:
        """reference: :443 Delete."""
        with self._cond:
            self.delete_nominated_pod_if_exists(pod)
            k = _pod_key(pod)
            qp = QueuedPodInfo(pod=pod)
            if not self.active_q.delete(qp):
                self.backoff_q.delete(qp)
                if self.unschedulable_q.pop(k, None) is not None:
                    if self._unschedulable_recorder:
                        self._unschedulable_recorder.dec()

    # -- cluster-event moves ------------------------------------------------

    def move_all_to_active_or_backoff_queue(self, event: str) -> None:
        """reference: :500."""
        with self._cond:
            self._move_pods(list(self.unschedulable_q.values()), event)

    def assigned_pod_added(self, pod: api.Pod) -> None:
        """A bound pod may unblock pods with (anti-)affinity
        (reference: :480 AssignedPodAdded / getUnschedulablePodsWithMatchingAffinityTerm :716)."""
        with self._cond:
            targets = [qp for qp in self.unschedulable_q.values()
                       if pod_with_affinity(qp.pod)]
            self._move_pods(targets, "AssignedPodAdded")

    assigned_pod_updated = assigned_pod_added

    def _move_pods(self, pods: List[QueuedPodInfo], event: str) -> None:
        # reference: :512 movePodsToActiveOrBackoffQueue
        moved = False
        for qp in pods:
            k = _pod_key(qp.pod)
            if k not in self.unschedulable_q:
                continue
            if self._is_backing_off(qp):
                self.backoff_q.add(qp)
                if self._metrics:
                    self._metrics.incoming(event, "backoff")
            else:
                self.active_q.add(qp)
                moved = True
                if self._metrics:
                    self._metrics.incoming(event, "active")
            del self.unschedulable_q[k]
            if self._unschedulable_recorder:
                self._unschedulable_recorder.dec()
        self.move_request_cycle = self.scheduling_cycle
        if moved:
            self._cond.notify_all()

    # -- periodic flushes ---------------------------------------------------

    def flush_backoff_completed(self) -> None:
        """reference: :244 flushBackoffQCompleted."""
        with self._cond:
            moved = False
            while True:
                qp = self.backoff_q.peek()
                if qp is None or self._backoff_time(qp) > self._clock():
                    break
                self.backoff_q.pop()
                self.active_q.add(qp)
                moved = True
                if self._metrics:
                    self._metrics.incoming("BackoffComplete", "active")
            if moved:
                self._cond.notify_all()

    def flush_unschedulable_leftover(self) -> None:
        """reference: :263 flushUnschedulableQLeftover."""
        with self._cond:
            now = self._clock()
            stale = [qp for qp in self.unschedulable_q.values()
                     if now - qp.timestamp > UNSCHEDULABLE_TIMEOUT]
            self._move_pods(stale, "UnschedulableTimeout")

    def run(self) -> None:
        """Start the flush goroutine-equivalents (reference: :241 Run)."""
        def loop(period, fn):
            while not self._stop.wait(period):
                fn()
        for period, fn in ((BACKOFF_FLUSH_PERIOD, self.flush_backoff_completed),
                           (UNSCHEDULABLE_FLUSH_PERIOD,
                            self.flush_unschedulable_leftover)):
            t = threading.Thread(target=loop, args=(period, fn), daemon=True)
            t.start()
            self._threads.append(t)

    def close(self) -> None:
        """Idempotent: stops the flush threads, wakes every blocked pop,
        and joins the flushers (with a timeout — they sleep up to their
        flush period on the stop event) so no daemon thread outlives the
        queue it mutates."""
        self._stop.set()
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        # join OUTSIDE the lock: a flusher mid-flush needs _cond to finish
        for t in self._threads:
            if t.is_alive():
                t.join(timeout=2.0)
        self._threads = []

    # -- introspection ------------------------------------------------------

    def depths(self) -> Dict[str, int]:
        """Per-queue depths in one locked read — the flight recorder
        stamps these on each cycle record at cycle start (the serving
        loop only calls this when the recorder is armed)."""
        with self._cond:
            return {"active": len(self.active_q),
                    "backoff": len(self.backoff_q),
                    "unschedulable": len(self.unschedulable_q)}

    def pending_pods(self) -> List[api.Pod]:
        """reference: :601 PendingPods."""
        with self._cond:
            return ([qp.pod for qp in self.active_q.list()]
                    + [qp.pod for qp in self.backoff_q.list()]
                    + [qp.pod for qp in self.unschedulable_q.values()])

    def __len__(self) -> int:
        with self._cond:
            return (len(self.active_q) + len(self.backoff_q)
                    + len(self.unschedulable_q))


def _pod_updates_may_make_schedulable(old: api.Pod, new: api.Pod) -> bool:
    """reference: scheduling_queue.go:422 isPodUpdated — generation-relevant
    fields (spec, labels, annotations) changed, ignoring status/resourceVersion."""
    return (old.spec != new.spec
            or old.metadata.labels != new.metadata.labels
            or old.metadata.annotations != new.metadata.annotations)
