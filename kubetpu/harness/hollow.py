"""Hollow cluster generation: synthetic node fleets and pod workloads.

The TPU-native analog of kubemark's hollow nodes (reference:
cmd/kubemark/hollow-node.go, pkg/kubemark/hollow_kubelet.go:35) and the
scheduler_perf node-prepare strategies (reference:
test/utils/runners.go:951-1121 TrivialNodePrepareStrategy/LabelNodeStrategy)
plus the benchmark node shape (reference:
test/integration/scheduler_perf/scheduler_test.go:52-66 — 110 pods, 4 CPU,
32 Gi per fake node).  Used by bench.py, __graft_entry__.py and the perf
harness to synthesize clusters without machines.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence, Tuple

from ..api import types as api


BENCH_NODE_CPU_MILLI = 4000          # scheduler_test.go:57 "4" cpu
BENCH_NODE_MEM_BYTES = 32 * (1 << 30)  # "32Gi"
BENCH_NODE_PODS = 110                # "110" pods


def make_node(name: str, zone: Optional[str] = None,
              region: Optional[str] = None,
              cpu_milli: int = BENCH_NODE_CPU_MILLI,
              mem: int = BENCH_NODE_MEM_BYTES,
              pods: int = BENCH_NODE_PODS,
              labels: Optional[Dict[str, str]] = None) -> api.Node:
    lab = {api.LABEL_HOSTNAME: name}
    if zone:
        lab[api.LABEL_ZONE] = zone
    if region:
        lab[api.LABEL_REGION] = region
    if labels:
        lab.update(labels)
    alloc = {"cpu": f"{cpu_milli}m", "memory": str(mem), "pods": str(pods)}
    return api.Node(
        metadata=api.ObjectMeta(name=name, labels=lab),
        status=api.NodeStatus(allocatable=dict(alloc), capacity=dict(alloc)))


def make_nodes(n: int, zones: int = 0, prefix: str = "node-",
               **kw) -> List[api.Node]:
    out = []
    for i in range(n):
        zone = f"zone-{i % zones}" if zones else None
        region = "region-0" if zones else None
        out.append(make_node(f"{prefix}{i}", zone=zone, region=region, **kw))
    return out


def make_pod(name: str, namespace: str = "default",
             cpu_milli: int = 100, mem: int = 256 << 20,
             labels: Optional[Dict[str, str]] = None,
             priority: int = 0) -> api.Pod:
    req = {"cpu": f"{cpu_milli}m", "memory": str(mem)}
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=namespace,
                                labels=dict(labels or {})),
        spec=api.PodSpec(
            priority=priority,
            containers=[api.Container(
                name="c", image="k8s.gcr.io/pause:3.2",
                resources=api.ResourceRequirements(requests=req))]))


def make_pods(n: int, prefix: str = "pod-", namespace: str = "default",
              group_labels: int = 0, rng: Optional[random.Random] = None,
              **kw) -> List[api.Pod]:
    """group_labels > 0 assigns each pod a label app=app-<i%groups> so
    affinity/spread workloads have selector targets."""
    rng = rng or random.Random(0)
    out = []
    for i in range(n):
        labels = {}
        if group_labels:
            labels["app"] = f"app-{i % group_labels}"
        out.append(make_pod(f"{prefix}{i}", namespace=namespace,
                            labels=labels, **kw))
    return out


def restart_world(n_nodes: int, existing_per_node: int = 2,
                  zones: int = 8):
    """The deterministic warm-restart world: n_nodes zoned nodes, each
    carrying existing_per_node bound pods with 16 app-group labels.
    SHARED by bench.py warm_restart_case and tools/kubeaot build_shape —
    a restart of shape (n_nodes, wave) dispatches byte-identical call
    forms to a capture of the same shape only because both sides build
    the world through this one function (same store insertion order,
    same label vocab, same selector diversity)."""
    from ..client.store import ClusterStore
    store = ClusterStore()
    for i, n in enumerate(make_nodes(n_nodes, zones=zones)):
        store.add(n)
        for p in make_pods(existing_per_node, prefix=f"ex-{i}-",
                           group_labels=16):
            p.spec.node_name = n.name
            store.add(p)
    return store


def restart_wave(wave: int, prefix: str = "restart-") -> List[api.Pod]:
    """The arriving wave of the warm-restart case: 16 app groups, 1/3
    soft zone spread, 1/5 hostname anti-affinity (the blended
    scheduler_perf topology mix).  Shared with tools/kubeaot build_shape
    for the same reason as restart_world."""
    pods = make_pods(wave, prefix=prefix, group_labels=16)
    for i, p in enumerate(pods):
        if i % 3 == 0:
            with_spread(p, api.LABEL_ZONE, when="ScheduleAnyway")
        if i % 5 == 0:
            with_anti_affinity(p)
    return pods


def with_spread(pod: api.Pod, topo_key: str, max_skew: int = 1,
                when: str = "DoNotSchedule",
                match: Optional[Dict[str, str]] = None) -> api.Pod:
    pod.spec.topology_spread_constraints.append(api.TopologySpreadConstraint(
        max_skew=max_skew, topology_key=topo_key, when_unsatisfiable=when,
        label_selector=api.LabelSelector(match_labels=dict(
            match or pod.metadata.labels))))
    return pod


def with_anti_affinity(pod: api.Pod, topo_key: str = api.LABEL_HOSTNAME,
                       match: Optional[Dict[str, str]] = None) -> api.Pod:
    term = api.PodAffinityTerm(
        label_selector=api.LabelSelector(match_labels=dict(
            match or pod.metadata.labels)),
        topology_key=topo_key)
    aff = pod.spec.affinity or api.Affinity()
    if aff.pod_anti_affinity is None:
        aff.pod_anti_affinity = api.PodAntiAffinity()
    aff.pod_anti_affinity.required_during_scheduling_ignored_during_execution \
        .append(term)
    pod.spec.affinity = aff
    return pod


def with_affinity(pod: api.Pod, topo_key: str = api.LABEL_ZONE,
                  match: Optional[Dict[str, str]] = None) -> api.Pod:
    term = api.PodAffinityTerm(
        label_selector=api.LabelSelector(match_labels=dict(
            match or pod.metadata.labels)),
        topology_key=topo_key)
    aff = pod.spec.affinity or api.Affinity()
    if aff.pod_affinity is None:
        aff.pod_affinity = api.PodAffinity()
    aff.pod_affinity.required_during_scheduling_ignored_during_execution \
        .append(term)
    pod.spec.affinity = aff
    return pod
