"""Hollow cluster generation: synthetic node fleets and pod workloads.

The TPU-native analog of kubemark's hollow nodes (reference:
cmd/kubemark/hollow-node.go, pkg/kubemark/hollow_kubelet.go:35) and the
scheduler_perf node-prepare strategies (reference:
test/utils/runners.go:951-1121 TrivialNodePrepareStrategy/LabelNodeStrategy)
plus the benchmark node shape (reference:
test/integration/scheduler_perf/scheduler_test.go:52-66 — 110 pods, 4 CPU,
32 Gi per fake node).  Used by bench.py, __graft_entry__.py and the perf
harness to synthesize clusters without machines.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..api import types as api


BENCH_NODE_CPU_MILLI = 4000          # scheduler_test.go:57 "4" cpu
BENCH_NODE_MEM_BYTES = 32 * (1 << 30)  # "32Gi"
BENCH_NODE_PODS = 110                # "110" pods


def make_node(name: str, zone: Optional[str] = None,
              region: Optional[str] = None,
              cpu_milli: int = BENCH_NODE_CPU_MILLI,
              mem: int = BENCH_NODE_MEM_BYTES,
              pods: int = BENCH_NODE_PODS,
              labels: Optional[Dict[str, str]] = None) -> api.Node:
    lab = {api.LABEL_HOSTNAME: name}
    if zone:
        lab[api.LABEL_ZONE] = zone
    if region:
        lab[api.LABEL_REGION] = region
    if labels:
        lab.update(labels)
    alloc = {"cpu": f"{cpu_milli}m", "memory": str(mem), "pods": str(pods)}
    return api.Node(
        metadata=api.ObjectMeta(name=name, labels=lab),
        status=api.NodeStatus(allocatable=dict(alloc), capacity=dict(alloc)))


def make_nodes(n: int, zones: int = 0, prefix: str = "node-",
               **kw) -> List[api.Node]:
    out = []
    for i in range(n):
        zone = f"zone-{i % zones}" if zones else None
        region = "region-0" if zones else None
        out.append(make_node(f"{prefix}{i}", zone=zone, region=region, **kw))
    return out


def make_pod(name: str, namespace: str = "default",
             cpu_milli: int = 100, mem: int = 256 << 20,
             labels: Optional[Dict[str, str]] = None,
             priority: int = 0) -> api.Pod:
    req = {"cpu": f"{cpu_milli}m", "memory": str(mem)}
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=namespace,
                                labels=dict(labels or {})),
        spec=api.PodSpec(
            priority=priority,
            containers=[api.Container(
                name="c", image="k8s.gcr.io/pause:3.2",
                resources=api.ResourceRequirements(requests=req))]))


def make_pods(n: int, prefix: str = "pod-", namespace: str = "default",
              group_labels: int = 0, rng: Optional[random.Random] = None,
              **kw) -> List[api.Pod]:
    """group_labels > 0 assigns each pod a label app=app-<i%groups> so
    affinity/spread workloads have selector targets."""
    rng = rng or random.Random(0)
    out = []
    for i in range(n):
        labels = {}
        if group_labels:
            labels["app"] = f"app-{i % group_labels}"
        out.append(make_pod(f"{prefix}{i}", namespace=namespace,
                            labels=labels, **kw))
    return out


def restart_world(n_nodes: int, existing_per_node: int = 2,
                  zones: int = 8):
    """The deterministic warm-restart world: n_nodes zoned nodes, each
    carrying existing_per_node bound pods with 16 app-group labels.
    SHARED by bench.py warm_restart_case and tools/kubeaot build_shape —
    a restart of shape (n_nodes, wave) dispatches byte-identical call
    forms to a capture of the same shape only because both sides build
    the world through this one function (same store insertion order,
    same label vocab, same selector diversity)."""
    from ..client.store import ClusterStore
    store = ClusterStore()
    for i, n in enumerate(make_nodes(n_nodes, zones=zones)):
        store.add(n)
        for p in make_pods(existing_per_node, prefix=f"ex-{i}-",
                           group_labels=16):
            p.spec.node_name = n.name
            store.add(p)
    return store


def restart_wave(wave: int, prefix: str = "restart-") -> List[api.Pod]:
    """The arriving wave of the warm-restart case: 16 app groups, 1/3
    soft zone spread, 1/5 hostname anti-affinity (the blended
    scheduler_perf topology mix).  Shared with tools/kubeaot build_shape
    for the same reason as restart_world."""
    pods = make_pods(wave, prefix=prefix, group_labels=16)
    for i, p in enumerate(pods):
        if i % 3 == 0:
            with_spread(p, api.LABEL_ZONE, when="ScheduleAnyway")
        if i % 5 == 0:
            with_anti_affinity(p)
    return pods


# -- sustained arrival/departure streams (the open-loop load vocabulary) --
#
# Every generator below returns a SEEDED, fully materialized event list
# [{"t": seconds-from-stream-start, "kind": "add"|"delete", "pod": Pod},
# ...] sorted by t — pure data, no clocks, no side effects — so the same
# (seed, rate, duration) tuple always yields the same stream and the
# armed-vs-disarmed parity golden can replay it deterministically.  The
# open-loop injection itself (fire each event at its wall deadline
# REGARDLESS of scheduler backpressure — the coordinated-omission
# defense) lives in harness/perf.py SustainedLoadRunner.


def _stream_pod(i: int, rng: random.Random, prefix: str,
                namespace: str, group_labels: int,
                spread_frac: float) -> api.Pod:
    labels = {"app": f"app-{i % group_labels}"} if group_labels else {}
    pod = make_pod(f"{prefix}{i}", namespace=namespace, labels=labels)
    # a slice of the stream carries SOFT zone spread (ScheduleAnyway):
    # the topology scoring path stays exercised under churn without
    # making any arrival infeasible (the steady-state gate expects
    # offered ~= completed and zero demotions on a healthy run)
    if spread_frac > 0 and rng.random() < spread_frac:
        with_spread(pod, api.LABEL_ZONE, when="ScheduleAnyway")
    return pod


def _with_departures(events: List[Dict[str, Any]], rng: random.Random,
                     mean_dwell_s: Optional[float]
                     ) -> List[Dict[str, Any]]:
    if not mean_dwell_s:
        return sorted(events, key=lambda e: e["t"])
    out = list(events)
    for e in events:
        if e["kind"] != "add":
            continue
        out.append({"t": e["t"] + rng.expovariate(1.0 / mean_dwell_s),
                    "kind": "delete", "pod": e["pod"]})
    return sorted(out, key=lambda e: e["t"])


def poisson_stream(rate: float, duration_s: float, seed: int = 0,
                   mean_dwell_s: Optional[float] = None,
                   prefix: str = "arr-", namespace: str = "default",
                   group_labels: int = 16,
                   spread_frac: float = 0.25) -> List[Dict[str, Any]]:
    """Homogeneous Poisson arrivals at ``rate`` pods/s for
    ``duration_s`` seconds (exponential inter-arrival gaps).  With
    ``mean_dwell_s``, each arrival also emits a departure event after
    an exponential dwell — continuous churn instead of monotone fill."""
    rng = random.Random(seed)
    events: List[Dict[str, Any]] = []
    t, i = 0.0, 0
    while True:
        t += rng.expovariate(rate)
        if t >= duration_s:
            break
        events.append({"t": t, "kind": "add",
                       "pod": _stream_pod(i, rng, prefix, namespace,
                                          group_labels, spread_frac)})
        i += 1
    return _with_departures(events, rng, mean_dwell_s)


def burst_stream(rate: float, duration_s: float, seed: int = 0,
                 burst_every_s: float = 10.0, burst_size: int = 64,
                 mean_dwell_s: Optional[float] = None,
                 prefix: str = "burst-", namespace: str = "default",
                 group_labels: int = 16,
                 spread_frac: float = 0.25) -> List[Dict[str, Any]]:
    """Baseline Poisson arrivals at ``rate`` plus a ``burst_size``-pod
    spike every ``burst_every_s`` seconds — the thundering-herd shape
    (deployment rollouts, cron fan-outs) that stresses queue depth and
    the recovery ladder rather than mean throughput."""
    rng = random.Random(seed)
    events: List[Dict[str, Any]] = []
    t, i = 0.0, 0
    while True:
        t += rng.expovariate(rate)
        if t >= duration_s:
            break
        events.append({"t": t, "kind": "add",
                       "pod": _stream_pod(i, rng, prefix, namespace,
                                          group_labels, spread_frac)})
        i += 1
    bt = burst_every_s
    while bt < duration_s:
        for _ in range(burst_size):
            events.append({"t": bt, "kind": "add",
                           "pod": _stream_pod(i, rng, prefix, namespace,
                                              group_labels, spread_frac)})
            i += 1
        bt += burst_every_s
    return _with_departures(events, rng, mean_dwell_s)


def diurnal_stream(rate: float, duration_s: float, seed: int = 0,
                   period_s: float = 60.0, amplitude: float = 0.5,
                   mean_dwell_s: Optional[float] = None,
                   prefix: str = "diurnal-", namespace: str = "default",
                   group_labels: int = 16,
                   spread_frac: float = 0.25) -> List[Dict[str, Any]]:
    """Nonhomogeneous Poisson arrivals whose instantaneous rate follows
    a sinusoid — ``rate * (1 + amplitude * sin(2*pi*t/period_s))`` —
    generated by thinning against the peak rate: the compressed-day
    shape (period_s plays 24 h) that exposes whether steady-state
    detection tracks a moving operating point instead of latching onto
    one plateau."""
    rng = random.Random(seed)
    peak = rate * (1.0 + abs(amplitude))
    events: List[Dict[str, Any]] = []
    t, i = 0.0, 0
    while True:
        t += rng.expovariate(peak)
        if t >= duration_s:
            break
        rate_t = rate * (1.0 + amplitude * math.sin(
            2.0 * math.pi * t / period_s))
        if rng.random() * peak >= max(rate_t, 0.0):
            continue
        events.append({"t": t, "kind": "add",
                       "pod": _stream_pod(i, rng, prefix, namespace,
                                          group_labels, spread_frac)})
        i += 1
    return _with_departures(events, rng, mean_dwell_s)


def with_spread(pod: api.Pod, topo_key: str, max_skew: int = 1,
                when: str = "DoNotSchedule",
                match: Optional[Dict[str, str]] = None) -> api.Pod:
    pod.spec.topology_spread_constraints.append(api.TopologySpreadConstraint(
        max_skew=max_skew, topology_key=topo_key, when_unsatisfiable=when,
        label_selector=api.LabelSelector(match_labels=dict(
            match or pod.metadata.labels))))
    return pod


def with_anti_affinity(pod: api.Pod, topo_key: str = api.LABEL_HOSTNAME,
                       match: Optional[Dict[str, str]] = None) -> api.Pod:
    term = api.PodAffinityTerm(
        label_selector=api.LabelSelector(match_labels=dict(
            match or pod.metadata.labels)),
        topology_key=topo_key)
    aff = pod.spec.affinity or api.Affinity()
    if aff.pod_anti_affinity is None:
        aff.pod_anti_affinity = api.PodAntiAffinity()
    aff.pod_anti_affinity.required_during_scheduling_ignored_during_execution \
        .append(term)
    pod.spec.affinity = aff
    return pod


def with_affinity(pod: api.Pod, topo_key: str = api.LABEL_ZONE,
                  match: Optional[Dict[str, str]] = None) -> api.Pod:
    term = api.PodAffinityTerm(
        label_selector=api.LabelSelector(match_labels=dict(
            match or pod.metadata.labels)),
        topology_key=topo_key)
    aff = pod.spec.affinity or api.Affinity()
    if aff.pod_affinity is None:
        aff.pod_affinity = api.PodAffinity()
    aff.pod_affinity.required_during_scheduling_ignored_during_execution \
        .append(term)
    pod.spec.affinity = aff
    return pod
