"""scheduler_perf: YAML-driven scheduling benchmark harness.

reference: test/integration/scheduler_perf/ — BenchmarkPerfScheduling
(scheduler_perf_test.go:117) reads config/performance-config.yaml (15
templated workloads), runs an in-process apiserver+scheduler
(util.go:60-68), samples 1-second throughput and scheduler histograms
(util.go:216-255) and emits perf-dashboard JSON DataItems
(scheduler_perf_types.go).  This module is the TPU-native clone: the
in-process ClusterStore plays the apiserver, hollow.make_* synthesize the
fleet (kubemark analog), and the same JSON shape comes out.

Run:  python -m kubetpu.harness.perf [--config config/performance-config.yaml]
"""

from __future__ import annotations

import json
import statistics
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..api import types as api
from ..apis.config import KubeSchedulerConfiguration, KubeSchedulerProfile
from ..client.store import ClusterStore
from ..scheduler import Scheduler
from ..utils.metrics import SchedulerMetrics
from . import hollow


@dataclass
class Workload:
    """One benchmark case (reference: performance-config.yaml template +
    params; scheduler_perf_test.go:64 testCase)."""
    name: str
    num_nodes: int = 100
    num_init_pods: int = 0
    num_pods_to_schedule: int = 100
    # pod template features
    pod_anti_affinity: bool = False          # required, hostname
    pod_affinity: bool = False               # required, zone
    preferred_pod_affinity: bool = False
    preferred_pod_anti_affinity: bool = False
    topology_spread: bool = False            # hard, zone
    preferred_topology_spread: bool = False  # soft, zone
    pvs: bool = False                        # one pre-bound PV/PVC per pod
    group_labels: int = 10
    zones: int = 8
    batch_size: int = 256
    # mixed mode: measured pods cycle through all enabled features
    mixed: bool = False


@dataclass
class DataItem:
    """reference: scheduler_perf_types.go DataItem."""
    data: Dict[str, float]
    unit: str
    labels: Dict[str, str]

    def to_doc(self):
        return {"data": self.data, "unit": self.unit, "labels": self.labels}


def _make_pod(w: Workload, i: int, prefix: str, store: ClusterStore) -> api.Pod:
    p = hollow.make_pod(f"{prefix}-{i}", cpu_milli=100, mem=250 << 20,
                        labels={"app": f"app-{i % w.group_labels}",
                                "group": prefix})
    features = []
    if w.pod_anti_affinity:
        features.append("anti")
    if w.pod_affinity:
        features.append("aff")
    if w.preferred_pod_affinity:
        features.append("paff")
    if w.preferred_pod_anti_affinity:
        features.append("panti")
    if w.topology_spread:
        features.append("spread")
    if w.preferred_topology_spread:
        features.append("pspread")
    if w.pvs:
        features.append("pv")
    if w.mixed and features:
        features = [features[i % len(features)]]
    for f in features:
        if f == "anti":
            hollow.with_anti_affinity(p, api.LABEL_HOSTNAME,
                                      match={"app": p.metadata.labels["app"]})
        elif f == "aff":
            hollow.with_affinity(p, api.LABEL_ZONE,
                                 match={"group": prefix})
            # seed pods must exist for required affinity to be satisfiable;
            # the bootstrap rule covers the first pod per selector
        elif f in ("paff", "panti"):
            aff = p.spec.affinity or api.Affinity()
            term = api.WeightedPodAffinityTerm(
                weight=10,
                pod_affinity_term=api.PodAffinityTerm(
                    label_selector=api.LabelSelector(
                        match_labels={"app": p.metadata.labels["app"]}),
                    topology_key=api.LABEL_ZONE))
            if f == "paff":
                aff.pod_affinity = aff.pod_affinity or api.PodAffinity()
                aff.pod_affinity.preferred_during_scheduling_ignored_during_execution.append(term)
            else:
                aff.pod_anti_affinity = aff.pod_anti_affinity or api.PodAntiAffinity()
                aff.pod_anti_affinity.preferred_during_scheduling_ignored_during_execution.append(term)
            p.spec.affinity = aff
        elif f == "spread":
            hollow.with_spread(p, api.LABEL_ZONE, max_skew=2,
                               when="DoNotSchedule",
                               match={"group": prefix})
        elif f == "pspread":
            hollow.with_spread(p, api.LABEL_ZONE, max_skew=1,
                               when="ScheduleAnyway",
                               match={"group": prefix})
        elif f == "pv":
            pv_name = f"pv-{prefix}-{i}"
            pvc_name = f"pvc-{prefix}-{i}"
            store.add(api.PersistentVolume(
                metadata=api.ObjectMeta(name=pv_name),
                storage_class_name="perf"))
            store.add(api.PersistentVolumeClaim(
                metadata=api.ObjectMeta(name=pvc_name),
                storage_class_name="perf", volume_name=pv_name))
            p.spec.volumes.append(api.Volume(
                name="v", persistent_volume_claim=pvc_name))
    return p


class ThroughputCollector:
    """1 Hz samples of pods scheduled per second
    (reference: util.go:216 throughputCollector)."""

    def __init__(self, store: ClusterStore, group: str):
        self.store = store
        self.group = group
        self.samples: List[float] = []

    def bound_count(self) -> int:
        return sum(1 for p in self.store.list("Pod")
                   if p.spec.node_name
                   and p.metadata.labels.get("group") == self.group)

    def run_until(self, target: int, timeout: float = 300.0,
                  interval: float = 1.0) -> bool:
        last = self.bound_count()
        deadline = time.time() + timeout
        while time.time() < deadline:
            time.sleep(interval)
            now = self.bound_count()
            self.samples.append((now - last) / interval)
            last = now
            if now >= target:
                return True
        return False


def _stats(samples: List[float]) -> Dict[str, float]:
    if not samples:
        return {"Average": 0.0, "Perc50": 0.0, "Perc90": 0.0, "Perc99": 0.0}
    s = sorted(samples)

    def perc(q):
        import math
        idx = min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))
        return s[idx]
    return {"Average": round(statistics.mean(s), 2),
            "Perc50": round(perc(0.50), 2),
            "Perc90": round(perc(0.90), 2),
            "Perc99": round(perc(0.99), 2)}


def run_workload(w: Workload, verbose: bool = False) -> List[DataItem]:
    """reference: scheduler_perf_test.go:117 perfScheduling."""
    store = ClusterStore()
    for n in hollow.make_nodes(w.num_nodes, zones=w.zones):
        store.add(n)
    if w.pvs:
        store.add(api.StorageClass(metadata=api.ObjectMeta(name="perf")))
    metrics = SchedulerMetrics()
    cfg = KubeSchedulerConfiguration(profiles=[KubeSchedulerProfile()],
                                     batch_size=w.batch_size)
    sched = Scheduler(store, config=cfg, metrics=metrics, async_binding=True)
    thread = sched.run()
    try:
        # phase 1: init pods (not measured)
        if w.num_init_pods:
            for i in range(w.num_init_pods):
                store.add(_make_pod(w, i, "init", store))
            coll = ThroughputCollector(store, "init")
            if not coll.run_until(w.num_init_pods):
                raise RuntimeError(
                    f"{w.name}: init pods did not schedule "
                    f"({coll.bound_count()}/{w.num_init_pods})")
        # phase 2: measured pods
        for i in range(w.num_pods_to_schedule):
            store.add(_make_pod(w, i, "measured", store))
        coll = ThroughputCollector(store, "measured")
        done = coll.run_until(w.num_pods_to_schedule)
        sched.wait_for_inflight_binds()
        scheduled = coll.bound_count()
        if verbose:
            print(f"  {w.name}: {scheduled}/{w.num_pods_to_schedule} "
                  f"scheduled", flush=True)
        items = [
            DataItem(data=_stats(coll.samples), unit="pods/s",
                     labels={"Name": w.name, "Metric": "SchedulingThroughput"}),
        ]
        for metric, hist in (
                ("scheduling_algorithm_duration_seconds",
                 metrics.scheduling_algorithm_duration),
                ("binding_duration_seconds", metrics.binding_duration),
                ("e2e_scheduling_duration_seconds",
                 metrics.e2e_scheduling_duration),
                ("pod_scheduling_duration_seconds",
                 metrics.pod_scheduling_duration)):
            n = hist.count()
            items.append(DataItem(
                data={"Average": round(hist.sum() / n, 6) if n else 0.0,
                      "Perc50": hist.percentile(0.50),
                      "Perc90": hist.percentile(0.90),
                      "Perc99": hist.percentile(0.99)},
                unit="s", labels={"Name": w.name, "Metric": metric}))
        if not done:
            items[0].labels["Incomplete"] = "true"
        return items
    finally:
        sched.close()


# the reference's workload matrix, scaled for one-box runs
# (reference: config/performance-config.yaml:1-120)
DEFAULT_WORKLOADS: List[Workload] = [
    Workload(name="SchedulingBasic", num_nodes=100, num_init_pods=100,
             num_pods_to_schedule=300),
    Workload(name="SchedulingPodAntiAffinity", num_nodes=100,
             num_init_pods=100, num_pods_to_schedule=150,
             pod_anti_affinity=True, group_labels=100),
    Workload(name="SchedulingPodAffinity", num_nodes=100, num_init_pods=100,
             num_pods_to_schedule=300, pod_affinity=True),
    Workload(name="SchedulingPreferredPodAffinity", num_nodes=100,
             num_init_pods=100, num_pods_to_schedule=300,
             preferred_pod_affinity=True),
    Workload(name="SchedulingPreferredPodAntiAffinity", num_nodes=100,
             num_init_pods=100, num_pods_to_schedule=300,
             preferred_pod_anti_affinity=True),
    Workload(name="TopologySpreading", num_nodes=100, num_init_pods=100,
             num_pods_to_schedule=300, topology_spread=True),
    Workload(name="PreferredTopologySpreading", num_nodes=100,
             num_init_pods=100, num_pods_to_schedule=300,
             preferred_topology_spread=True),
    Workload(name="SchedulingInTreePVs", num_nodes=100, num_init_pods=50,
             num_pods_to_schedule=100, pvs=True),
    Workload(name="MixedSchedulingBasePod", num_nodes=100, num_init_pods=200,
             num_pods_to_schedule=300, pod_anti_affinity=True,
             pod_affinity=True, preferred_pod_affinity=True,
             topology_spread=True, mixed=True),
]


def load_workloads(path: str) -> List[Workload]:
    import yaml
    with open(path) as f:
        docs = yaml.safe_load(f)
    if not isinstance(docs, list) or not all(isinstance(d, dict)
                                             for d in docs):
        raise SystemExit(f"{path}: expected a YAML list of workload "
                         "mappings (see config/performance-config.yaml)")
    out = []
    for d in docs:
        try:
            out.append(Workload(**d))
        except TypeError as e:
            raise SystemExit(f"{path}: bad workload {d.get('name', d)}: {e}")
    return out


def main(argv: Optional[List[str]] = None) -> None:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=None,
                    help="YAML workload list (default: built-in matrix)")
    ap.add_argument("--only", default=None, help="substring workload filter")
    ap.add_argument("--out", default=None, help="write DataItems JSON here")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    workloads = (load_workloads(args.config) if args.config
                 else DEFAULT_WORKLOADS)
    if args.only:
        workloads = [w for w in workloads if args.only.lower() in
                     w.name.lower()]
    all_items = []
    for w in workloads:
        if args.verbose:
            print(f"running {w.name} ({w.num_nodes} nodes, "
                  f"{w.num_pods_to_schedule} pods)...", flush=True)
        items = run_workload(w, verbose=args.verbose)
        all_items.extend(items)
    doc = {"version": "v1",
           "dataItems": [it.to_doc() for it in all_items]}
    text = json.dumps(doc, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    print(text)


if __name__ == "__main__":
    main()
