"""scheduler_perf: YAML-driven scheduling benchmark harness.

reference: test/integration/scheduler_perf/ — BenchmarkPerfScheduling
(scheduler_perf_test.go:117) reads config/performance-config.yaml (15
templated workloads), runs an in-process apiserver+scheduler
(util.go:60-68), samples 1-second throughput and scheduler histograms
(util.go:216-255) and emits perf-dashboard JSON DataItems
(scheduler_perf_types.go).  This module is the TPU-native clone: the
in-process ClusterStore plays the apiserver, hollow.make_* synthesize the
fleet (kubemark analog), and the same JSON shape comes out.

Run:  python -m kubetpu.harness.perf [--config config/performance-config.yaml]
"""

from __future__ import annotations

import json
import statistics
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..api import types as api
from ..apis.config import KubeSchedulerConfiguration, KubeSchedulerProfile
from ..client.store import ClusterStore
from ..scheduler import Scheduler
from ..utils import trace as _utrace
from ..utils.metrics import SchedulerMetrics
from . import hollow


def host_share(device_wait_s: float, elapsed_s: float) -> float:
    """ONE definition of the serial-exposure number every reporting
    surface shares (bench.py's run_mode / pv_heavy cases and the perf
    harness's SchedulerStats below — it used to be computed inline in
    each): the fraction of wall time NOT spent blocked on the per-cycle
    packed readback, i.e. the host-side share of the drain the depth-k
    pipelined executor (kubetpu/pipeline.py) exists to hide."""
    return round(1.0 - device_wait_s / max(elapsed_s, 1e-9), 3)


@dataclass
class Workload:
    """One benchmark case (reference: performance-config.yaml template +
    params; scheduler_perf_test.go:64 testCase)."""
    name: str
    num_nodes: int = 100
    num_init_pods: int = 0
    num_pods_to_schedule: int = 100
    # pod template features
    pod_anti_affinity: bool = False          # required, hostname
    pod_affinity: bool = False               # required, zone
    preferred_pod_affinity: bool = False
    preferred_pod_anti_affinity: bool = False
    topology_spread: bool = False            # hard, zone
    preferred_topology_spread: bool = False  # soft, zone
    pvs: bool = False                        # one pre-bound in-tree PV/PVC
    secrets: bool = False                    # secret volume (no constraint)
    csi_pvs: bool = False                    # CSI PV/PVC + CSINode limits
    migrated_pvs: bool = False               # in-tree PV under CSINode limits
                                             # (CSI-migration translation is a
                                             # documented deviation; counts
                                             # land on the in-tree filter)
    node_affinity: bool = False              # required node affinity on zone
    preemption: bool = False                 # init: low-priority fillers;
                                             # measured: high-priority pods
    unschedulable: bool = False              # init: node-sized cpu hogs
    skip_wait_init: bool = False             # don't wait for init pods
                                             # (reference: Unschedulable's
                                             # skipWaitUntilInitPodsScheduled)
    group_labels: int = 10
    zones: int = 8
    batch_size: int = 256
    timeout_s: float = 300.0   # per-phase scheduling deadline
    mode: str = "gang"         # serving default; "sequential" = exact
                               # serial-replay oracle
    # mixed mode: measured pods cycle through all enabled features
    mixed: bool = False


@dataclass
class DataItem:
    """reference: scheduler_perf_types.go DataItem."""
    data: Dict[str, float]
    unit: str
    labels: Dict[str, str]

    def to_doc(self):
        return {"data": self.data, "unit": self.unit, "labels": self.labels}


def _make_pod(w: Workload, i: int, prefix: str, store: ClusterStore) -> api.Pod:
    # special init/measured template splits (reference: Preemption and
    # Unschedulable templates use different init vs measured pod YAMLs)
    if w.preemption and prefix == "init":
        # low-priority fillers, four per 4-cpu node (reference:
        # pod-low-priority.yaml; 2000 init / 500 nodes)
        return hollow.make_pod(f"{prefix}-{i}", cpu_milli=900,
                               mem=250 << 20, priority=-10,
                               labels={"group": prefix})
    if w.unschedulable and prefix == "init":
        # cpu ask EXCEEDS a whole node (reference: pod-large-cpu.yaml asks
        # more than node capacity) — these pods must stay pending and
        # churn the unschedulable queue while measured pods flow
        return hollow.make_pod(f"{prefix}-{i}", cpu_milli=4900,
                               mem=250 << 20, labels={"group": prefix})
    # preemption's measured pods ask for more cpu than the fillers leave
    # free, so every placement must evict a victim (PostFilter path)
    preempting = w.preemption and prefix == "measured"
    p = hollow.make_pod(f"{prefix}-{i}",
                        cpu_milli=600 if preempting else 100,
                        mem=250 << 20,
                        priority=100 if preempting else 0,
                        labels={"app": f"app-{i % w.group_labels}",
                                "group": prefix})
    features = []
    if w.pod_anti_affinity:
        features.append("anti")
    if w.pod_affinity:
        features.append("aff")
    if w.preferred_pod_affinity:
        features.append("paff")
    if w.preferred_pod_anti_affinity:
        features.append("panti")
    if w.topology_spread:
        features.append("spread")
    if w.preferred_topology_spread:
        features.append("pspread")
    if w.pvs:
        features.append("pv")
    if w.secrets:
        features.append("secret")
    if w.csi_pvs:
        features.append("csipv")
    if w.migrated_pvs:
        features.append("migpv")
    if w.node_affinity:
        features.append("nodeaff")
    if w.mixed:
        # reference MixedSchedulingBasePod: INIT pods cycle through the
        # feature templates; MEASURED pods are plain default pods
        features = ([features[i % len(features)]]
                    if prefix == "init" and features else [])
    for f in features:
        if f == "anti":
            hollow.with_anti_affinity(p, api.LABEL_HOSTNAME,
                                      match={"app": p.metadata.labels["app"]})
        elif f == "aff":
            hollow.with_affinity(p, api.LABEL_ZONE,
                                 match={"group": prefix})
            # seed pods must exist for required affinity to be satisfiable;
            # the bootstrap rule covers the first pod per selector
        elif f in ("paff", "panti"):
            aff = p.spec.affinity or api.Affinity()
            term = api.WeightedPodAffinityTerm(
                weight=10,
                pod_affinity_term=api.PodAffinityTerm(
                    label_selector=api.LabelSelector(
                        match_labels={"app": p.metadata.labels["app"]}),
                    topology_key=api.LABEL_ZONE))
            if f == "paff":
                aff.pod_affinity = aff.pod_affinity or api.PodAffinity()
                aff.pod_affinity.preferred_during_scheduling_ignored_during_execution.append(term)
            else:
                aff.pod_anti_affinity = aff.pod_anti_affinity or api.PodAntiAffinity()
                aff.pod_anti_affinity.preferred_during_scheduling_ignored_during_execution.append(term)
            p.spec.affinity = aff
        elif f == "spread":
            hollow.with_spread(p, api.LABEL_ZONE, max_skew=2,
                               when="DoNotSchedule",
                               match={"group": prefix})
        elif f == "pspread":
            hollow.with_spread(p, api.LABEL_ZONE, max_skew=1,
                               when="ScheduleAnyway",
                               match={"group": prefix})
        elif f in ("pv", "csipv", "migpv"):
            pv_name = f"pv-{prefix}-{i}"
            pvc_name = f"pvc-{prefix}-{i}"
            pv = api.PersistentVolume(
                metadata=api.ObjectMeta(name=pv_name),
                storage_class_name="perf")
            if f == "csipv":
                # reference: pv-csi.yaml + csiNodeAllocatable 39/node
                pv.csi_driver = "ebs.csi.aws.com"
                pv.csi_volume_handle = pv_name
            else:
                # in-tree EBS source; "migpv" keeps the in-tree source but
                # the cluster also carries CSINode limits (the migration
                # TRANSLATION itself is a documented deviation)
                pv.aws_elastic_block_store = pv_name
            store.add(pv)
            store.add(api.PersistentVolumeClaim(
                metadata=api.ObjectMeta(name=pvc_name),
                storage_class_name="perf", volume_name=pv_name))
            p.spec.volumes.append(api.Volume(
                name="v", persistent_volume_claim=pvc_name))
        elif f == "secret":
            # a secret volume constrains nothing at scheduling time — the
            # workload measures the volume-bearing fast path (reference:
            # pod-with-secret-volume.yaml)
            p.spec.volumes.append(api.Volume(name="secret"))
        elif f == "nodeaff":
            # required node affinity on the zone label (reference:
            # pod-with-node-affinity.yaml In [zone-0 zone-1])
            aff = p.spec.affinity or api.Affinity()
            aff.node_affinity = api.NodeAffinity(
                required_during_scheduling_ignored_during_execution=(
                    api.NodeSelector(node_selector_terms=[
                        api.NodeSelectorTerm(match_expressions=[
                            api.NodeSelectorRequirement(
                                key=api.LABEL_ZONE, operator="In",
                                values=["zone-0", "zone-1"])])])))
            p.spec.affinity = aff
    return p


class ThroughputCollector:
    """1 Hz samples of pods scheduled per second
    (reference: util.go:216 throughputCollector)."""

    def __init__(self, store: ClusterStore, group: str):
        self.store = store
        self.group = group
        self.samples: List[float] = []

    def bound_count(self) -> int:
        return sum(1 for p in self.store.list("Pod")
                   if p.spec.node_name
                   and p.metadata.labels.get("group") == self.group)

    def run_until(self, target: int, timeout: float = 300.0,
                  interval: float = 1.0) -> bool:
        last = self.bound_count()
        deadline = time.time() + timeout
        while time.time() < deadline:
            time.sleep(interval)
            now = self.bound_count()
            self.samples.append((now - last) / interval)
            last = now
            if now >= target:
                return True
        return False


def _p50(xs: List[int]) -> int:
    return sorted(xs)[len(xs) // 2] if xs else 0


def _stats(samples: List[float]) -> Dict[str, float]:
    if not samples:
        return {"Average": 0.0, "Perc50": 0.0, "Perc90": 0.0, "Perc99": 0.0}
    s = sorted(samples)

    def perc(q):
        import math
        idx = min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))
        return s[idx]
    return {"Average": round(statistics.mean(s), 2),
            "Perc50": round(perc(0.50), 2),
            "Perc90": round(perc(0.90), 2),
            "Perc99": round(perc(0.99), 2)}


class SustainedLoadRunner:
    """OPEN-LOOP sustained-load driver: fires a seeded arrival/departure
    event stream (hollow.poisson_stream / burst_stream / diurnal_stream)
    at its WALL DEADLINES against a live serving scheduler — each event
    fires when its timestamp says, REGARDLESS of scheduler backpressure.
    That is the coordinated-omission defense: a closed-loop driver that
    waits for the scheduler before offering the next pod silently
    excludes exactly the requests a slow scheduler would have made wait,
    so its latency numbers flatter every stall.  Here the OFFERED rate
    is fixed by the stream and the COMPLETED rate is measured
    separately; the gap between them (plus ``behind_max_s``, how far the
    injector itself fell behind its deadlines) is reported, never
    hidden.

    The latency verdict comes from the windowed telemetry ring
    (utils/telemetry.py, armed by the caller): per-window e2e p99 with
    warmup excluded by the steady-state slope test — not a
    run-cumulative quantile that averages warmup compiles into the
    steady number."""

    def __init__(self, store: ClusterStore, sched: Scheduler,
                 events: List[Dict[str, Any]], duration_s: float,
                 settle_s: float = 30.0):
        self.store = store
        self.sched = sched
        self.events = events
        self.duration_s = float(duration_s)
        self.settle_s = float(settle_s)

    def run(self) -> Dict[str, Any]:
        from ..utils import telemetry as _telemetry
        offered = deletes = completed_deleted = 0
        behind_max = 0.0
        added: List[tuple] = []
        t0 = time.time()
        for e in self.events:
            deadline = t0 + e["t"]
            now = time.time()
            if deadline > now:
                time.sleep(deadline - now)
            else:
                behind_max = max(behind_max, now - deadline)
            pod = e["pod"]
            if e["kind"] == "add":
                self.store.add(pod)
                offered += 1
                added.append((pod.namespace, pod.metadata.name))
            else:
                cur = self.store.get_pod(pod.namespace, pod.metadata.name)
                if cur is not None:
                    # a pod bound before its departure still COMPLETED —
                    # the churn deletes it from the cluster, not from
                    # the ledger
                    if cur.spec.node_name:
                        completed_deleted += 1
                    self.store.delete(cur)
                    deletes += 1

        def bound_now() -> int:
            n = 0
            for ns, name in added:
                p = self.store.get_pod(ns, name)
                if p is not None and p.spec.node_name:
                    n += 1
            return n

        # settle: the tail of the stream drains CLOSED-loop (arrivals
        # have stopped; this phase is excluded from the offered-rate
        # denominator and, via the slope test, from steady-state windows)
        settle_deadline = time.time() + self.settle_s
        completed = completed_deleted + bound_now()
        while completed < offered and time.time() < settle_deadline:
            time.sleep(0.2)
            completed = completed_deleted + bound_now()
        self.sched.wait_for_inflight_binds()
        completed = completed_deleted + bound_now()
        out: Dict[str, Any] = {
            "duration_s": round(self.duration_s, 3),
            "offered": offered,
            "offered_rate": round(offered / max(self.duration_s, 1e-9), 2),
            "completed": completed,
            "completed_rate": round(
                completed / max(self.duration_s, 1e-9), 2),
            "completed_frac": round(completed / max(offered, 1), 4),
            "deletes": deletes,
            "behind_max_s": round(behind_max, 3),
        }
        tel = _telemetry.ring()
        if tel is not None:
            # close the tail window so the last arrivals land in a
            # recorded window, then read the steady-state verdict
            tel.force_roll(self.sched)
            out["load"] = tel.digest()
        return out


def run_workload(w: Workload, verbose: bool = False) -> List[DataItem]:
    """reference: scheduler_perf_test.go:117 perfScheduling."""
    store = ClusterStore()
    for n in hollow.make_nodes(w.num_nodes, zones=w.zones):
        store.add(n)
        if w.csi_pvs or w.migrated_pvs:
            # reference: nodeAllocatableStrategy csiNodeAllocatable 39
            store.add(api.CSINode(
                metadata=api.ObjectMeta(name=n.name),
                driver_allocatable={"ebs.csi.aws.com": 39}))
    if w.pvs or w.csi_pvs or w.migrated_pvs:
        store.add(api.StorageClass(metadata=api.ObjectMeta(name="perf")))
    metrics = SchedulerMetrics()
    cfg = KubeSchedulerConfiguration(profiles=[KubeSchedulerProfile()],
                                     batch_size=w.batch_size, mode=w.mode,
                                     chain_cycles=True)
    sched = Scheduler(store, config=cfg, metrics=metrics, async_binding=True)
    thread = sched.run()
    try:
        # phase 1: init pods (not measured)
        if w.num_init_pods:
            for i in range(w.num_init_pods):
                store.add(_make_pod(w, i, "init", store))
            if w.skip_wait_init:
                # reference: skipWaitUntilInitPodsScheduled — some init
                # pods may be unschedulable by design; give the queue one
                # flush interval to absorb them
                time.sleep(2.0)
            else:
                coll = ThroughputCollector(store, "init")
                if not coll.run_until(w.num_init_pods,
                                      timeout=w.timeout_s):
                    raise RuntimeError(
                        f"{w.name}: init pods did not schedule "
                        f"({coll.bound_count()}/{w.num_init_pods})")
        # phase 2: measured pods
        device_wait0 = sched.device_wait_s
        cycles0 = sched.cycle_count
        resyncs0 = sched.resync_count
        delta0 = sched.delta_cycle_count
        t_measured = time.time()
        for i in range(w.num_pods_to_schedule):
            store.add(_make_pod(w, i, "measured", store))
        coll = ThroughputCollector(store, "measured")
        done = coll.run_until(w.num_pods_to_schedule,
                              timeout=w.timeout_s)
        sched.wait_for_inflight_binds()
        elapsed = time.time() - t_measured
        scheduled = coll.bound_count()
        if verbose:
            print(f"  {w.name}: {scheduled}/{w.num_pods_to_schedule} "
                  f"scheduled", flush=True)
        device_wait = sched.device_wait_s - device_wait0
        items = [
            DataItem(data=_stats(coll.samples), unit="pods/s",
                     labels={"Name": w.name, "Metric": "SchedulingThroughput"}),
            # measured-phase scheduler internals (same split bench.py
            # reports for its direct-drive cases): committed cycles, wall
            # time spent blocked on the per-cycle packed readback, and the
            # host share of the measured phase
            DataItem(data={"Cycles": float(sched.cycle_count - cycles0),
                           "DeviceWaitS": round(device_wait, 3),
                           "HostShare": host_share(device_wait,
                                                   elapsed),
                           # incremental-tensorization health (state/delta)
                           # over the MEASURED phase only, like Cycles:
                           # rows the scatter path updated per delta cycle
                           # + how often the blessed full rebuild ran
                           # the measured-phase tail of the bounded ring:
                           # the monotonic cycle counter stays correct
                           # even after the deque evicts warm-up entries
                           "Resyncs": float(sched.resync_count - resyncs0),
                           "DeltaRowsP50": float(_p50(
                               list(sched.delta_rows)[
                                   -(sched.delta_cycle_count - delta0):]
                               if sched.delta_cycle_count > delta0
                               else []))},
                     unit="mixed",
                     labels={"Name": w.name, "Metric": "SchedulerStats"}),
        ]
        fr = _utrace.flight_recorder()
        if fr is not None:
            # flight-recorder health next to the perf numbers: how many of
            # the run's cycles the ring still holds and how many it shed
            items.append(DataItem(
                data={"Cycles": float(len(fr.cycles())),
                      "Dropped": float(fr.dropped())},
                unit="count",
                labels={"Name": w.name, "Metric": "FlightRecorder"}))
        for metric, hist in (
                ("scheduling_algorithm_duration_seconds",
                 metrics.scheduling_algorithm_duration),
                ("binding_duration_seconds", metrics.binding_duration),
                ("e2e_scheduling_duration_seconds",
                 metrics.e2e_scheduling_duration),
                ("pod_scheduling_duration_seconds",
                 metrics.pod_scheduling_duration)):
            n = hist.count()
            items.append(DataItem(
                data={"Average": round(hist.sum() / n, 6) if n else 0.0,
                      "Perc50": hist.percentile(0.50),
                      "Perc90": hist.percentile(0.90),
                      "Perc99": hist.percentile(0.99)},
                unit="s", labels={"Name": w.name, "Metric": metric}))
        if not done:
            items[0].labels["Incomplete"] = "true"
        return items
    finally:
        sched.close()


# the reference's workload matrix, scaled for one-box runs
# (reference: config/performance-config.yaml:1-120)
DEFAULT_WORKLOADS: List[Workload] = [
    Workload(name="SchedulingBasic", num_nodes=100, num_init_pods=100,
             num_pods_to_schedule=300),
    Workload(name="SchedulingPodAntiAffinity", num_nodes=100,
             num_init_pods=100, num_pods_to_schedule=150,
             pod_anti_affinity=True, group_labels=100),
    Workload(name="SchedulingPodAffinity", num_nodes=100, num_init_pods=100,
             num_pods_to_schedule=300, pod_affinity=True),
    Workload(name="SchedulingPreferredPodAffinity", num_nodes=100,
             num_init_pods=100, num_pods_to_schedule=300,
             preferred_pod_affinity=True),
    Workload(name="SchedulingPreferredPodAntiAffinity", num_nodes=100,
             num_init_pods=100, num_pods_to_schedule=300,
             preferred_pod_anti_affinity=True),
    Workload(name="TopologySpreading", num_nodes=100, num_init_pods=100,
             num_pods_to_schedule=300, topology_spread=True),
    Workload(name="PreferredTopologySpreading", num_nodes=100,
             num_init_pods=100, num_pods_to_schedule=300,
             preferred_topology_spread=True),
    Workload(name="SchedulingInTreePVs", num_nodes=100, num_init_pods=50,
             num_pods_to_schedule=100, pvs=True),
    Workload(name="SchedulingSecrets", num_nodes=100, num_init_pods=100,
             num_pods_to_schedule=300, secrets=True),
    Workload(name="SchedulingCSIPVs", num_nodes=100, num_init_pods=50,
             num_pods_to_schedule=100, csi_pvs=True),
    Workload(name="SchedulingMigratedInTreePVs", num_nodes=100,
             num_init_pods=50, num_pods_to_schedule=100, migrated_pvs=True),
    Workload(name="SchedulingNodeAffinity", num_nodes=100, num_init_pods=100,
             num_pods_to_schedule=300, node_affinity=True),
    Workload(name="MixedSchedulingBasePod", num_nodes=100, num_init_pods=200,
             num_pods_to_schedule=300, pod_anti_affinity=True,
             pod_affinity=True, preferred_pod_affinity=True,
             topology_spread=True, mixed=True),
    Workload(name="Preemption", num_nodes=100, num_init_pods=400,
             num_pods_to_schedule=100, preemption=True),
    Workload(name="Unschedulable", num_nodes=100, num_init_pods=40,
             num_pods_to_schedule=200, unschedulable=True,
             skip_wait_init=True),
]


def _write_doc(path: str, items: List[DataItem]) -> None:
    """Atomic checkpoint write: a crash mid-matrix (e.g. a TPU worker
    fault an hour in) must not lose — or truncate — the completed
    workloads' results."""
    import os
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"version": "v1",
                   "dataItems": [it.to_doc() for it in items]}, f, indent=2)
    os.replace(tmp, path)


def load_workloads(path: str) -> List[Workload]:
    import yaml
    with open(path) as f:
        docs = yaml.safe_load(f)
    if not isinstance(docs, list) or not all(isinstance(d, dict)
                                             for d in docs):
        raise SystemExit(f"{path}: expected a YAML list of workload "
                         "mappings (see config/performance-config.yaml)")
    out = []
    for d in docs:
        try:
            out.append(Workload(**d))
        except TypeError as e:
            raise SystemExit(f"{path}: bad workload {d.get('name', d)}: {e}")
    return out


def main(argv: Optional[List[str]] = None) -> None:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=None,
                    help="YAML workload list (default: built-in matrix)")
    ap.add_argument("--only", default=None, help="substring workload filter")
    ap.add_argument("--out", default=None, help="write DataItems JSON here")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    workloads = (load_workloads(args.config) if args.config
                 else DEFAULT_WORKLOADS)
    if args.only:
        workloads = [w for w in workloads if args.only.lower() in
                     w.name.lower()]
    all_items = []
    failed: List[str] = []
    for w in workloads:
        if args.verbose:
            print(f"running {w.name} ({w.num_nodes} nodes, "
                  f"{w.num_pods_to_schedule} pods)...", flush=True)
        try:
            items = run_workload(w, verbose=args.verbose)
        except Exception as e:
            # one failed workload must not lose the rest of the matrix —
            # record it, keep going, and exit non-zero at the end
            print(f"  {w.name} FAILED: {e}", file=sys.stderr, flush=True)
            failed.append(w.name)
            items = [DataItem(data=_stats([]), unit="pods/s",
                              labels={"Name": w.name,
                                      "Metric": "SchedulingThroughput",
                                      "Error": str(e)})]
        all_items.extend(items)
        if args.out:
            _write_doc(args.out, all_items)
    if args.out and not workloads:
        # zero workloads ran (e.g. --only matched nothing): still refresh
        # the file so a stale previous run can't masquerade as current
        _write_doc(args.out, all_items)
    doc = {"version": "v1",
           "dataItems": [it.to_doc() for it in all_items]}
    print(json.dumps(doc, indent=2))
    if failed:
        print(f"{len(failed)} workload(s) failed: {', '.join(failed)}",
              file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
