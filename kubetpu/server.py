"""Serving: /healthz, /metrics, /configz endpoints.

reference: cmd/kube-scheduler/app/server.go:167-199 (health + metrics
servers on the secure/insecure ports, configz registration) and
staging/src/k8s.io/component-base/configz.
"""

from __future__ import annotations

import json
import threading
from dataclasses import asdict, is_dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class SchedulerServer:
    def __init__(self, scheduler, host: str = "127.0.0.1", port: int = 10251):
        self.scheduler = scheduler
        self.host, self.port = host, port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        sched = self.scheduler

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: str,
                      ctype: str = "text/plain; charset=utf-8"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/healthz":
                    self._send(200, "ok")
                elif self.path == "/metrics":
                    if sched.metrics is None:
                        self._send(200, "")
                    else:
                        self._send(200, sched.metrics.expose_text(),
                                   "text/plain; version=0.0.4")
                elif self.path == "/configz":
                    cfg = sched.config
                    doc = asdict(cfg) if is_dataclass(cfg) else vars(cfg)
                    self._send(200, json.dumps(doc, default=str, indent=2),
                               "application/json")
                else:
                    self._send(404, "not found")

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd = None
