"""Serving: /healthz, /metrics, /configz and the /debug observability
endpoints.

reference: cmd/kube-scheduler/app/server.go:167-199 (health + metrics
servers on the secure/insecure ports, configz registration) and
staging/src/k8s.io/component-base/configz.  The /debug family is the
TPU-native analog of the reference's pprof/debug endpoints
(DebuggingConfiguration): ``/debug/flightz`` dumps the flight recorder's
ring (``?format=chrome`` returns Perfetto-loadable Chrome trace-event
JSON), ``/debug/explain?pod=<name>[&namespace=<ns>]`` answers the per-pod
"why (un)scheduled" audit from the scheduler's DecisionLog (no pod
parameter lists the most recent decisions; ``?outcome=unschedulable``
filters), and ``/debug/slo`` serves the per-pod latency SLO document
(utils/slo.py: per-stage p50/p90/p99/p999 + worst-pod exemplars linking
to the flight-recorder cycle and decision-audit entry; 404 while the
tracker is disarmed, ``?stage=`` filters, bad parameters are 400).
``/debug/journal`` reports the durable cycle journal's status
(utils/journal.py: records, bytes, drops, window span, linkage
hit-rates into the live flight/decision rings; ``armed: false`` when
KUBETPU_JOURNAL is unset).  ``/debug/devicez`` serves device-side
observability (utils/devstats.py: measured per-program device time with
the roofline join, the HBM residency ledger, fence-overhead accounting;
404 while KUBETPU_DEVSTATS is disarmed, ``?program=`` filters, unknown
programs are 400).  ``/debug/loadz`` serves the sustained-load telemetry
ring (utils/telemetry.py: per-window stage quantiles, queue depths,
recovery/demotion events, journal/flight drops, device deltas, plus the
steady-state digest; 404 while KUBETPU_TELEMETRY is disarmed, ``?n=``
limits to the newest n windows, bad parameters are 400).
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from dataclasses import asdict, is_dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .utils import devstats as udevstats
from .utils import journal as ujournal
from .utils import slo as uslo
from .utils import telemetry as utelemetry
from .utils import trace as utrace


class SchedulerServer:
    def __init__(self, scheduler, host: str = "127.0.0.1", port: int = 10251):
        self.scheduler = scheduler
        self.host, self.port = host, port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        sched = self.scheduler

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: str,
                      ctype: str = "text/plain; charset=utf-8"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _send_json(self, code: int, doc) -> None:
                self._send(code, json.dumps(doc, default=str, indent=2),
                           "application/json")

            def _flightz(self, query) -> None:
                fr = utrace.flight_recorder()
                if fr is None:
                    self._send_json(200, {
                        "armed": False,
                        "hint": "arm with KUBETPU_FLIGHT=1 or "
                                "kubetpu.utils.trace.arm_flight_recorder()"})
                    return
                fmt = (query.get("format") or ["json"])[0]
                if fmt in ("chrome", "perfetto"):
                    self._send_json(200, fr.to_chrome_trace())
                else:
                    doc = fr.to_dict()
                    # a saved flightz dump feeds traceview's "SLO:"
                    # digest too when the latency tracker is armed
                    trk = uslo.tracker()
                    if trk is not None:
                        doc["slo"] = {"stages": trk.stage_quantiles(),
                                      "shares": trk.shares()}
                    self._send_json(200, doc)

            def _explain(self, query) -> None:
                log = getattr(sched, "decisions", None)
                if log is None or not log.enabled:
                    self._send_json(200, {
                        "enabled": False,
                        "hint": "the decision audit is off "
                                "(KUBETPU_AUDIT=0)"})
                    return
                pod = (query.get("pod") or [None])[0]
                if not pod:
                    outcome = (query.get("outcome") or [None])[0]
                    try:
                        n = int((query.get("n") or ["50"])[0])
                    except ValueError:
                        self._send_json(400, {
                            "error": "n must be an integer"})
                        return
                    self._send_json(200, log.to_dict(n, outcome=outcome))
                    return
                ns = (query.get("namespace") or [None])[0]
                decision = log.get(pod, namespace=ns)
                if decision is None:
                    self._send_json(404, {
                        "error": f"no recorded decision for pod {pod!r}",
                        "hint": "the DecisionLog is bounded; the pod may "
                                "not have been attempted yet or its entry "
                                "was evicted"})
                    return
                self._send_json(200, decision.to_dict())

            def _slo(self, query) -> None:
                trk = uslo.tracker()
                if trk is None:
                    self._send_json(404, {
                        "armed": False,
                        "error": "the SLO tracker is disarmed",
                        "hint": "arm with KUBETPU_SLO=1 or "
                                "kubetpu.utils.slo.arm_slo_tracker()"})
                    return
                doc = trk.to_dict()
                stage = (query.get("stage") or [None])[0]
                if stage is not None:
                    if stage not in doc["stages"]:
                        self._send_json(400, {
                            "error": f"unknown stage {stage!r}",
                            "stages": sorted(doc["stages"])})
                        return
                    doc["stages"] = {stage: doc["stages"][stage]}
                raw_n = (query.get("n") or [None])[0]
                if raw_n is not None:
                    try:
                        n = int(raw_n)
                        if n < 0:
                            raise ValueError
                    except ValueError:
                        self._send_json(400, {
                            "error": "n must be a non-negative integer"})
                        return
                    doc["exemplars"] = doc["exemplars"][:n]
                self._send_json(200, doc)

            def _devicez(self, query) -> None:
                ds = udevstats.devstats()
                if ds is None:
                    self._send_json(404, {
                        "armed": False,
                        "error": "device-side observability is disarmed",
                        "hint": "arm with KUBETPU_DEVSTATS=1 or "
                                "kubetpu.utils.devstats.arm_devstats()"})
                    return
                doc = ds.to_dict()
                program = (query.get("program") or [None])[0]
                if program is not None:
                    if program not in doc["programs"]:
                        self._send_json(400, {
                            "error": f"unknown program {program!r}",
                            "programs": sorted(doc["programs"])})
                        return
                    doc["programs"] = {program: doc["programs"][program]}
                self._send_json(200, doc)

            def _loadz(self, query) -> None:
                tel = utelemetry.ring()
                if tel is None:
                    self._send_json(404, {
                        "armed": False,
                        "error": "the telemetry ring is disarmed",
                        "hint": "arm with KUBETPU_TELEMETRY=1 or "
                                "kubetpu.utils.telemetry.arm_telemetry()"})
                    return
                raw_n = (query.get("n") or [None])[0]
                last = None
                if raw_n is not None:
                    try:
                        last = int(raw_n)
                        if last < 0:
                            raise ValueError
                    except ValueError:
                        self._send_json(400, {
                            "error": "n must be a non-negative integer"})
                        return
                self._send_json(200, tel.to_dict(last=last))

            def _journal(self, query) -> None:
                jr = ujournal.journal()
                if jr is None:
                    self._send_json(200, {
                        "armed": False,
                        "hint": "arm with KUBETPU_JOURNAL=<dir> or "
                                "kubetpu.utils.journal.arm_journal()"})
                    return
                fr = utrace.flight_recorder()
                flight_seqs = ({r.seq for r in fr.cycles()}
                               if fr is not None else None)
                log = getattr(sched, "decisions", None)
                decision_cycles = None
                if log is not None and log.enabled:
                    decision_cycles = {d.cycle
                                       for d in log.recent(log.capacity)}
                doc = jr.status(flight_seqs=flight_seqs,
                                decision_cycles=decision_cycles)
                doc["replay_hint"] = ("python -m tools.kubereplay "
                                      + jr.dir)
                self._send_json(200, doc)

            def do_GET(self):
                parsed = urllib.parse.urlparse(self.path)
                path = parsed.path
                query = urllib.parse.parse_qs(parsed.query)
                if path == "/healthz":
                    self._send(200, "ok")
                elif path == "/metrics":
                    # Prometheus text exposition format 0.0.4 content
                    # type either way (an empty registry is still a
                    # valid scrape)
                    body = ("" if sched.metrics is None
                            else sched.metrics.expose_text())
                    self._send(200, body, "text/plain; version=0.0.4")
                elif path == "/configz":
                    cfg = sched.config
                    doc = asdict(cfg) if is_dataclass(cfg) else vars(cfg)
                    self._send_json(200, doc)
                elif path == "/debug/flightz":
                    self._flightz(query)
                elif path == "/debug/explain":
                    self._explain(query)
                elif path == "/debug/slo":
                    self._slo(query)
                elif path == "/debug/journal":
                    self._journal(query)
                elif path == "/debug/devicez":
                    self._devicez(query)
                elif path == "/debug/loadz":
                    self._loadz(query)
                else:
                    self._send(404, "not found")

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd = None
            if self._thread is not None:
                self._thread.join(timeout=2.0)
                self._thread = None
