"""Preemption: wave-batched what-if victim selection.

reference: pkg/scheduler/core/generic_scheduler.go — Preempt :252,
podEligibleToPreemptOthers :1063, nodesWherePreemptionMightHelp :1041,
selectNodesForPreemption :858, selectVictimsOnNode :949 (clone + remove
lower-priority pods + re-run filters + reprieve by PDB then priority),
processPreemptionWithExtenders :317, pickOneNodeForPreemption :729
(6-criteria lexicographic tie-break); invoked from scheduler.go:391 preempt.

TPU shape of the what-if: the reference clones one NodeInfo per candidate
and serially re-runs all filter plugins per victim add-back — an
O(candidates x victims) host loop, run once per failed pod.  Here BOTH
loops are batched:

  * the candidate axis is vmapped — every candidate's what-if state is the
    shared cycle snapshot plus a per-candidate delta, and one device pass
    answers "does the pod now fit" for ALL candidates at once; the
    reprieve loop is a lax.scan over add-back depth (PDB-violating first,
    then by descending priority — :1004-1037), so device passes per
    preemption = reprieve depth + 1, independent of the candidate count;

  * the FAILED-POD axis is batched too (preempt_wave): every
    preemption-eligible FitError of a scheduling cycle is served by ONE
    [B, C, K] what-if program (models/programs.py whatif_wave) built from
    vectorized numpy victim tensors (CycleContext.victim_index), instead
    of one candidates pass + one what-if dispatch per pod.  Cross-pod
    contention — two preemptors claiming one node — resolves host-side in
    ranked commit order: the higher pick_one_node_for_preemption rank wins
    the node, losers fall back to their next-ranked candidate, and pods
    left without a fresh candidate are re-waved against the updated
    overlay for a small fixed number of rounds (like the gang auction).
    Winners' victim deletions and nominations land on the shared
    CycleContext commit overlay (note_evict / the queue nominator), so
    later rounds see earlier evictions without re-tensorizing — a
    deviation from the reference's one-pod-per-cycle snapshot reuse that
    only ever AVOIDS needless double-eviction (no victim is ever deleted
    twice).

Pods whose what-if can perturb topology verdicts (own spread constraints
or affinity terms, or any existing-pod filter term in the cluster) keep
the exact per-pod reprieve (_whatif_reprieve, pod_valid masking included);
term-free pods — the common preemption workload — take the resource-only
wave kernel, whose non-fit filter verdicts are provably constant across
victim removal (whatif_static_ok).

The cycle's snapshot tensors are reused (reference Preempt reuses the
Schedule call's nodeInfoSnapshot); nothing is re-tensorized per failed pod.

Host-filter deviation: see README.md "Preemption" — volume-type (host)
filters are validated against the final victim-adjusted NodeInfo instead
of inside every reprieve step.
"""

from __future__ import annotations

import functools
import time
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import numpy as np

from .api import types as api
from .framework.interface import CycleState
from .framework.types import NodeInfo, PodInfo
from .models import programs
from .models.batch import PodBatchBuilder
from .state.tensors import (MIB, CH_PODS, SnapshotBuilder,
                            resource_to_channels)
from .utils.intern import pow2_bucket
from .utils.trace import flight_span


class Victims:
    __slots__ = ("pods", "num_pdb_violations")

    def __init__(self, pods: List[api.Pod], num_pdb_violations: int):
        self.pods = pods
        self.num_pdb_violations = num_pdb_violations


def _pod_channels(pi: PodInfo, table, R: int) -> np.ndarray:
    """A pod's resource request as cluster channels (CH_PODS = 1).  Unknown
    scalar resources resolve to channel -1 and are skipped — a victim may
    carry an extended resource no node ever registered."""
    vec = resource_to_channels(pi.resource, table, R, intern_new=False)
    vec[CH_PODS] = 1.0
    return vec


class _NodeVictims(NamedTuple):
    """One node's evictable-pod index, priority-descending (stable order —
    the reprieve order of :1004-1037 before PDB partitioning)."""
    prios: np.ndarray   # [V] i32, descending
    snap_pos: np.ndarray  # [V] i32 — position in ni.pods snapshot order
                          # (the PDB disruption budget consumes in THIS
                          # order, filterPodsWithPDBViolation :1118)
    rows: np.ndarray    # [V] i32 existing-pod tensor rows (-1 unknown)
    req: np.ndarray     # [V, R] f32 request channels (CH_PODS = 1)
    nz: np.ndarray      # [V, 2] f32 (non-zero cpu milli, mem MiB)
    ts: np.ndarray      # [V] f64 creation timestamps
    pis: tuple          # PodInfo per victim, same order
    uids: tuple         # pod uid per victim, same order


class CycleContext:
    """Per-cycle tensors the scheduler shares with preemption (reference:
    Preempt runs against the same g.nodeInfoSnapshot as Schedule).  Also
    caches per-pod feasibility rows so N failed pods cost ONE candidates
    pass, not N."""

    def __init__(self, builder: SnapshotBuilder, cluster, cfg,
                 node_infos: Sequence[NodeInfo], batch=None,
                 row_of: Optional[Dict[str, int]] = None,
                 feasible=None, unresolvable=None):
        self.builder = builder
        self.cluster = cluster
        self.cfg = cfg
        self.node_infos = node_infos
        self.batch = batch           # the cycle's PodBatch (all live pods)
        self.row_of = row_of or {}   # pod uid -> batch row
        self.feasible = feasible     # [B, N] np.ndarray or None
        self.unresolvable = unresolvable
        # same-cycle committed placements, overlaid before any what-if: the
        # reference's reused nodeInfoSnapshot serves exactly ONE pod per
        # cycle; with B pods per cycle a pod failing late in the batch must
        # see the capacity already claimed by earlier commits or preemption
        # overestimates free space and deletes victims for nothing
        self.commit_req = None       # [N, R] np — committed request channels
        self.commit_nz = None        # [N, 2] np
        self.commit_ports = None     # [N, P] np bool — committed host ports
        self.commits = 0
        self._verdict_commits = 0
        self._cluster_cache = None   # (commits, overlaid cluster)
        self._lazy = None            # (feasible_dev, unresolvable_dev)
        self.pod_rows = None         # uid -> existing-pod tensor row (set
                                     # by the scheduler; required when the
                                     # cluster is CHAINED and rows no
                                     # longer follow node_infos order)
        self._has_filter_terms = None  # lazy: any valid existing
                                       # anti-affinity term in the cluster
        # node row -> _NodeVictims (lazy, one host pass per cycle)
        self._victim_index = None
        # wave results by pod uid (nominated node name or None) — the
        # PostFilter per-pod path short-circuits on these
        self.wave_nominated: Dict[str, Optional[str]] = {}
        # victims evicted THIS cycle, shared by every wave/preempt call
        # against this context: the victim_index is a cycle-lifetime cache,
        # so a later attempt must not re-select (and re-subtract) a victim
        # an earlier wave already deleted
        self.evicted_uids: set = set()

    def has_filter_terms(self) -> bool:
        """Does the cluster carry ANY valid existing-pod required
        anti-affinity term?  (One tiny readback, cached per cycle.)  When
        False, removing victims cannot change the InterPodAffinity verdict
        of a term-less preemptor, so the what-if may drop that filter."""
        if self._has_filter_terms is None:
            self._has_filter_terms = bool(
                np.asarray(self.cluster.filter_terms.valid).any())
        return self._has_filter_terms

    def set_lazy_verdicts(self, feasible_dev, unresolvable_dev) -> None:
        """Share DEVICE verdict arrays without forcing a transfer: they
        materialize only if a preemption attempt actually reads them with
        no commits in between (otherwise a refresh supersedes them and the
        multi-MB device->host copy never happens)."""
        self._lazy = (feasible_dev, unresolvable_dev)

    def _ensure_overlay(self) -> None:
        if self.commit_req is None:
            shape = self.cluster.requested.shape
            self.commit_req = np.zeros(shape, np.float32)
            self.commit_nz = np.zeros((shape[0], 2), np.float32)
            self.commit_ports = np.zeros(
                (shape[0], self.cluster.ports.shape[1]), bool)

    def note_commit(self, row: int, node_row: int) -> None:
        """Record a committed batch placement (batch row -> node row)."""
        if self.batch is None:
            return
        self._ensure_overlay()
        self.commit_req[node_row] += np.asarray(self.batch.req[row])
        self.commit_nz[node_row] += np.asarray(self.batch.nonzero_req[row])
        self.commit_ports[node_row] |= (
            np.asarray(self.batch.ports_asnode_hot[row]) > 0.5)
        self.commits += 1

    def note_evict(self, node_row: int, req_vec: np.ndarray,
                   nz_vec: np.ndarray) -> None:
        """Record a deleted preemption victim so later wave rounds (and
        later preemption attempts this cycle) see the freed capacity
        without re-tensorizing.  Ports are NOT restored — matching the
        serial what-if, which never adjusted them either (conservative:
        a victim's host ports stay blocked until the next snapshot)."""
        self._ensure_overlay()
        self.commit_req[node_row] -= req_vec
        self.commit_nz[node_row] -= nz_vec
        self.commits += 1

    def cluster_now(self):
        """The cycle's cluster tensors with committed placements overlaid
        (resource/pod-count channels and host ports; committed pods'
        topology terms are not overlaid — a bounded deviation, matching the
        nominated-pods overlay's scope in the reference,
        generic_scheduler.go:541-545)."""
        if self.commits == 0:
            return self.cluster
        if (self._cluster_cache is not None
                and self._cluster_cache[0] == self.commits):
            return self._cluster_cache[1]
        import jax.numpy as jnp
        cl = self.cluster._replace(
            requested=self.cluster.requested + jnp.asarray(self.commit_req),
            nonzero_requested=(self.cluster.nonzero_requested
                               + jnp.asarray(self.commit_nz)),
            ports=self.cluster.ports | jnp.asarray(self.commit_ports))
        self._cluster_cache = (self.commits, cl)
        return cl

    def pod_verdicts(self, pod_uid: str):
        """(feasible_row, unresolvable_row) for a cycle pod, computing the
        whole-batch filter pass lazily on first use (one device call shared
        by every preemption attempt this cycle).  Verdicts taken before the
        latest commit are STALE — a gang-mode pod that lost purely to
        intra-batch contention has round-0 feasibility on nodes that are now
        full, which would exclude exactly the cheapest preemption
        candidates; returning None routes the caller to its single-pod
        [1, N] pass against cluster_now(), far cheaper than re-running the
        whole [B, N] batch per failing pod."""
        row = self.row_of.get(pod_uid)
        if row is None:
            return None
        self._materialize_lazy()
        if self.feasible is not None and self._verdict_commits != self.commits:
            return None
        if self.feasible is None:
            if self.batch is None:
                return None
            self.refresh_verdicts()
        return self.feasible[row], self.unresolvable[row]

    def _materialize_lazy(self) -> None:
        """Pull the auction's device verdict arrays to host IF they are
        still current (no commits since) and nothing fresher exists."""
        if self.feasible is None and self._lazy is not None \
                and self.commits == 0:
            self.feasible = np.asarray(self._lazy[0])
            self.unresolvable = np.asarray(self._lazy[1])

    def refresh_verdicts(self) -> None:
        """One whole-batch filter pass against the CURRENT committed state,
        shared by every preemption attempt that follows.  The scheduler
        calls this once after the commit loop so N failed pods cost one
        [B, N] pass, not N single-pod passes."""
        feasible, unresolvable = programs.filter_verdicts(
            self.cluster_now(), self.batch, self.cfg)
        self.feasible = np.asarray(feasible)
        self.unresolvable = np.asarray(unresolvable)
        self._verdict_commits = self.commits

    def min_pod_priority(self):
        """Lowest priority among all existing pods (lazily computed once
        per cycle), or None when the cluster has no pods.  A preemptor
        whose priority is <= this can never find a victim, so preemption
        short-circuits without any device pass (the reference reaches the
        same conclusion inside selectVictimsOnNode, one candidate at a
        time)."""
        if not hasattr(self, "_min_prio"):
            prios = [pi.pod.priority() for ni in self.node_infos
                     for pi in ni.pods]
            self._min_prio = min(prios) if prios else None
        return self._min_prio

    def pod_row_map(self) -> Dict[str, int]:
        """pod uid -> existing-pod tensor row (cached for the cycle, like
        victim_index which consumes it).  Chained clusters carry the
        mapping explicitly (rows diverge from build order); otherwise it is
        the build order of state/tensors.py SnapshotBuilder.build."""
        if self.pod_rows is not None:
            return self.pod_rows
        if getattr(self, "_pod_row_cache", None) is None:
            rows: Dict[str, int] = {}
            row = 0
            for ni in self.node_infos:
                for pi in ni.pods:
                    rows[pi.pod.uid] = row
                    row += 1
            self._pod_row_cache = rows
        return self._pod_row_cache

    def victim_index(self) -> Dict[int, _NodeVictims]:
        """node row -> priority-ordered victim arrays, built in ONE host
        pass over the snapshot and shared by every wave round and every
        preemptor this cycle.  Replaces the per-(pod, candidate) Python
        loops that re-walked ni.pods and re-assembled resource vectors for
        every failed pod."""
        if self._victim_index is None:
            table = self.builder.table
            R = int(self.cluster.requested.shape[1])
            pod_rows = self.pod_row_map()
            out: Dict[int, _NodeVictims] = {}
            for j, ni in enumerate(self.node_infos):
                if not ni.pods:
                    continue
                prios = np.fromiter((pi.pod.priority() for pi in ni.pods),
                                    np.int64, len(ni.pods))
                order = np.argsort(-prios, kind="stable")
                pis = [ni.pods[int(k)] for k in order]
                out[j] = _NodeVictims(
                    prios=prios[order].astype(np.int32),
                    snap_pos=order.astype(np.int32),
                    rows=np.fromiter(
                        (pod_rows.get(pi.pod.uid, -1) for pi in pis),
                        np.int32, len(pis)),
                    req=np.stack([_pod_channels(pi, table, R)
                                  for pi in pis]),
                    nz=np.array([[pi.non_zero_cpu, pi.non_zero_mem / MIB]
                                 for pi in pis], np.float32),
                    ts=np.fromiter(
                        (pi.pod.metadata.creation_timestamp or 0.0
                         for pi in pis), np.float64, len(pis)),  # kubelint: ignore[numeric/f64] host-only pickOne tie-break; f32 quantizes unix seconds to ~256 s and never reaches the device
                    pis=tuple(pis),
                    uids=tuple(pi.pod.uid for pi in pis))
            self._victim_index = out
        return self._victim_index


@functools.partial(jax.jit, static_argnames=("cfg",))
def _whatif_reprieve(cluster, batch1, cfg, cand_rows, rm_valid, rm_req,
                     rm_nz, vic_row, vic_req, vic_nz):
    """Batched selectVictimsOnNode (generic_scheduler.go:949) for ONE pod
    whose what-if needs pod_valid masking (topology terms in play); the
    term-free wave path runs models/programs.py whatif_wave instead.

    cand_rows [C]        candidate node rows
    rm_valid  [C, P]     pod_valid with ALL of each candidate's lower-priority
                         pods masked out
    rm_req    [C, R]     summed resources of those pods (per own node row)
    rm_nz     [C, 2]     their non-zero-request sums
    vic_row   [C, K]     victim pod rows in reprieve order (-1 pad)
    vic_req   [C, K, R]  per-victim resources
    vic_nz    [C, K, 2]

    Returns (fits0 [C] — pod fits with all victims removed,
             reprieved [K, C] — victim k stayed on the node)."""
    import jax.numpy as jnp

    from .models.batch import densify_for
    batch1 = densify_for(cluster, batch1)
    C = cand_rows.shape[0]
    K = vic_row.shape[1]
    base_req = cluster.requested
    base_nz = cluster.nonzero_requested

    def one(pod_valid, dreq, dnz, row):
        cl = cluster._replace(
            pod_valid=pod_valid,
            requested=base_req.at[row].add(-dreq),
            nonzero_requested=base_nz.at[row].add(-dnz))
        feas, _, _ = programs.run_filters(cl, batch1, cfg)
        return feas[0]  # [N]

    vfilter = jax.vmap(one, in_axes=(0, 0, 0, 0))

    def verdicts(pod_valid, dreq, dnz):
        feas = vfilter(pod_valid, dreq, dnz, cand_rows)       # [C, N]
        return jnp.take_along_axis(feas, cand_rows[:, None], 1)[:, 0]

    fits0 = verdicts(rm_valid, rm_req, rm_nz)

    def step(carry, k):
        pod_valid, dreq, dnz, ok = carry
        row = vic_row[:, k]                                   # [C]
        exists = (row >= 0) & ok
        e = exists.astype(jnp.float32)
        try_valid = pod_valid.at[jnp.arange(C), jnp.clip(row, 0)].max(exists)
        try_dreq = dreq - vic_req[:, k] * e[:, None]
        try_dnz = dnz - vic_nz[:, k] * e[:, None]
        fit = verdicts(try_valid, try_dreq, try_dnz) & exists
        keep = fit[:, None]
        pod_valid = jnp.where(keep, try_valid, pod_valid)
        dreq = jnp.where(keep, try_dreq, dreq)
        dnz = jnp.where(keep, try_dnz, dnz)
        return (pod_valid, dreq, dnz, ok), fit

    (_, _, _, _), reprieved = jax.lax.scan(
        step, (rm_valid, rm_req, rm_nz, fits0), jnp.arange(K))
    return fits0, reprieved


class Preemptor:
    def __init__(self, scheduler, max_candidates: int = 2048,
                 wave_rounds: int = 4):
        self.sched = scheduler
        # memory bound on the vmapped candidate axis, NOT the reference's
        # behavior — when exceeded, candidates are pre-ranked and trimmed
        self.max_candidates = max_candidates
        # contention-resolution rounds per wave: pods left without a fresh
        # candidate after losing a node re-enter the next round's what-if
        # against the updated eviction/nomination overlay; leftovers after
        # the cap fail cleanly (requeue + retry next cycle)
        self.wave_rounds = wave_rounds
        # element budget for one [B, C, K, R] wave tensor set — beyond it
        # the wave splits along the pod axis (keeps HBM bounded at
        # pathological candidate x victim fan-out)
        self.max_wave_elements = 1 << 26

    # ------------------------------------------------------------------ entry

    def preempt(self, fwk, state: CycleState, pod: api.Pod,
                cycle: Optional[CycleContext] = None) -> Optional[str]:
        """reference: scheduler.go:391 + generic_scheduler.go:252 Preempt.
        Returns the nominated node name, or None.  A thin wrapper over a
        1-pod wave; when the scheduler already served this pod in the
        cycle's batched wave, the recorded verdict is returned as-is."""
        if cycle is not None and pod.uid in cycle.wave_nominated:
            return cycle.wave_nominated[pod.uid]
        return self.preempt_wave(fwk, cycle, [pod]).get(pod.uid)

    def preempt_wave(self, fwk, cycle: Optional[CycleContext],
                     pods: Sequence[api.Pod]) -> Dict[str, Optional[str]]:
        """Serve every preemption-eligible failed pod of a cycle with ONE
        batched what-if per contention round.  Returns pod uid -> nominated
        node name (None = no preemption).  Victim deletions and nominations
        are committed in ranked order as part of the wave; results are also
        recorded on the CycleContext so the per-pod PostFilter path
        short-circuits."""
        sched = self.sched
        results: Dict[str, Optional[str]] = {}
        alias: Dict[str, str] = {}   # caller uid -> store-refreshed uid
        fresh: List[api.Pod] = []
        for pod in pods:
            p = sched.store.get_pod(pod.namespace, pod.metadata.name) or pod
            results[p.uid] = None
            if p.uid != pod.uid:
                alias[pod.uid] = p.uid
            # reference: podEligibleToPreemptOthers runs before any
            # candidates work — an ineligible pod must not cost a snapshot
            # tensorization on the cycle-less direct path
            if self._eligible(p):
                fresh.append(p)
        if fresh and sched.metrics is not None:
            # reference: metrics.PreemptionAttempts.Inc() per Preempt call
            sched.metrics.preemption_attempts.inc(amount=len(fresh))
        if fresh and cycle is None:
            cycle = self._build_cycle(fwk, fresh)
        try:
            if fresh and cycle.node_infos:
                self._run_wave(fwk, cycle, fresh, results)
        except BaseException:
            # record only COMMITTED winners: their victims are gone and a
            # re-attempt must not double-preempt — but unserved pods must
            # stay eligible for the scheduler's per-pod fallback
            if cycle is not None:
                cycle.wave_nominated.update(
                    {uid: n for uid, n in results.items() if n})
            raise
        for orig, ref in alias.items():
            results[orig] = results[ref]
        if cycle is not None:
            cycle.wave_nominated.update(results)
        return results

    def _run_wave(self, fwk, cycle: CycleContext, pods: List[api.Pod],
                  results: Dict[str, Optional[str]]) -> None:
        sched = self.sched
        min_prio = cycle.min_pod_priority()
        if min_prio is None:
            return
        # nothing anywhere is evictable by a pod at/below the cluster's
        # minimum priority — skip the whole candidates/what-if machinery
        # (eligibility was already filtered by preempt_wave)
        live = [p for p in pods if p.priority() > min_prio]
        if not live:
            return
        # ranked commit order: priority-descending, queue order within ties
        # (the reference's serial drain pops by priority too)
        live.sort(key=lambda p: -p.priority())
        pdbs = sched.store.list("PodDisruptionBudget")
        node_row = {ni.node_name: j
                    for j, ni in enumerate(cycle.node_infos)}
        # cycle-scoped, not wave-scoped: a later preempt call against this
        # same context (extender path, wave-failure fallback) must see the
        # victims this wave deletes, or the stale victim_index would hand
        # them out — and note_evict would subtract them — twice
        deleted = cycle.evicted_uids
        pending = live
        has_preempt_ext = any(e.supports_preemption()
                              for e in sched.extenders)
        for _ in range(self.wave_rounds):
            fastw, slow_entries = self._wave_round(fwk, cycle, pending,
                                                   pdbs, deleted)
            claimed: set = set()
            next_pending: List[api.Pod] = []
            for pod in pending:
                b = fastw.index.get(pod.uid) if fastw is not None else None
                if b is not None and not has_preempt_ext:
                    # lazy lexicographic resolution: only the WINNER's
                    # victim list materializes (a full node_victims dict
                    # per pod re-created the per-pod host loops this wave
                    # exists to kill)
                    best, victims, had_claimed = fastw.resolve(
                        fwk, self, pod, b, claimed)
                else:
                    nv = (slow_entries.get(pod.uid)
                          if pod.uid in slow_entries
                          else (fastw.entries_dict(fwk, self, pod, b)
                                if b is not None else {}))
                    had_claimed = any(n in claimed for n in nv)
                    if had_claimed:
                        # a higher-ranked preemptor won this node in THIS
                        # round; its entry predates that claim — fall back
                        # to the next-ranked candidates, or re-wave
                        nv = {n: v for n, v in nv.items()
                              if n not in claimed}
                    nv = self._process_with_extenders(pod, nv)
                    best = pick_one_node_for_preemption(nv) if nv else None
                    victims = nv.get(best) if best is not None else None
                if best is None:
                    if had_claimed:
                        next_pending.append(pod)
                    continue
                self._commit_victims(fwk, pod, best, victims, cycle,
                                     node_row[best])
                deleted.update(p.uid for p in victims.pods)
                claimed.add(best)
                results[pod.uid] = best
            pending = next_pending
            if not pending:
                break

    def _commit_victims(self, fwk, pod: api.Pod, best: str,
                        victims: Victims, cycle: CycleContext,
                        node_row: int) -> None:
        """Delete the chosen victims and nominate the preemptor
        (reference: scheduler.go:403-415), recording the evictions on the
        cycle overlay so later wave rounds see the freed capacity."""
        sched = self.sched
        table = cycle.builder.table
        R = int(cycle.cluster.requested.shape[1])
        if victims.pods and sched.metrics is not None:
            # reference: metrics.PreemptionVictims.Observe per preemptor
            sched.metrics.preemption_victims.observe(len(victims.pods))
        for victim in victims.pods:
            try:
                sched.store.delete(victim)
            except Exception:
                # already gone (raced external delete): nothing was freed,
                # so neither the event nor the overlay subtraction applies
                continue
            if sched.recorder:
                sched.recorder.event(victim, "Normal", "Preempted",
                                     f"by {pod.namespace}/{pod.metadata.name} "
                                     f"on node {best}")
            pi = PodInfo(victim)
            cycle.note_evict(node_row, _pod_channels(pi, table, R),
                             np.asarray([pi.non_zero_cpu,
                                         pi.non_zero_mem / MIB], np.float32))
        # reject lower-priority waiting (Permit) pods on the node
        def maybe_reject(wp):
            if (wp.pod.priority() < pod.priority()):
                wp.reject("preempted")
        fwk.iterate_over_waiting_pods(maybe_reject)
        # clear nomination of lower-priority pods nominated to this node
        for np_ in sched.queue.nominated_pods_for_node(best):
            if np_.priority() < pod.priority():
                sched.queue.delete_nominated_pod_if_exists(np_)
        sched.queue.add_nominated_pod(pod, best)

    def _eligible(self, pod: api.Pod) -> bool:
        """reference: generic_scheduler.go:1063 podEligibleToPreemptOthers —
        if the pod already nominated a node and a lower-priority pod there
        is terminating, wait instead of preempting again."""
        nominated = pod.status.nominated_node_name
        if not nominated:
            return True
        ni = self.sched.snapshot.get(nominated)
        if ni is None:
            return True
        for pi in ni.pods:
            if (pi.pod.metadata.deletion_timestamp is not None
                    and pi.pod.priority() < pod.priority()):
                return False
        return True

    # ------------------------------------------------------------ cycle state

    def _build_cycle(self, fwk, pods: Sequence[api.Pod]) -> CycleContext:
        """Fallback when no cycle tensors were handed over (direct calls,
        extender path)."""
        sched = self.sched
        sched.cache.update_snapshot(sched.snapshot)
        node_infos = list(sched.snapshot.node_info_list)
        builder = SnapshotBuilder(
            hard_pod_affinity_weight=fwk.hard_pod_affinity_weight)
        builder.intern_pending([PodInfo(p) for p in pods])
        cluster = builder.build(node_infos).to_device()
        cfg = programs.ProgramConfig(
            filters=fwk.tensor_filters, scores=fwk.tensor_scores,
            hostname_topokey=max(
                builder.table.topokey.get(api.LABEL_HOSTNAME), 0),
            plugin_args=fwk.tensor_plugin_args(builder.table))
        return CycleContext(builder=builder, cluster=cluster, cfg=cfg,
                            node_infos=node_infos)

    def _pods_batch(self, pods: Sequence[api.Pod], cycle: CycleContext):
        import jax
        pb = PodBatchBuilder(cycle.builder.table)
        sels = [self.sched.store.default_spread_selector(p) for p in pods]
        return jax.tree.map(np.asarray,
                            pb.build([PodInfo(p) for p in pods],
                                     spread_selectors=sels))

    def _pod_batch1(self, pod: api.Pod, cycle: CycleContext):
        return self._pods_batch([pod], cycle)

    def _cluster_with_nominated(self, pod: api.Pod, cycle: CycleContext):
        """cluster_now plus equal/higher-priority nominated pods' resources
        on their nominated rows — the preemption simulation must respect
        capacity other preemptors already reserved (reference:
        addNominatedPods inside fitsOnNode, generic_scheduler.go:594).
        Wave winners are visible here too: their nominations land in the
        queue nominator at commit time, before the next round's entries."""
        import jax.numpy as jnp
        from .models.batch import build_nominated
        cl = cycle.cluster_now()
        prio = pod.priority()
        node_row = {ni.node_name: j
                    for j, ni in enumerate(cycle.node_infos)}
        entries = []
        for p, nn in self.sched.queue.all_nominated():
            if p.uid == pod.uid or p.priority() < prio:
                continue
            row = node_row.get(nn)
            if row is None:
                continue
            entries.append((PodInfo(p), row))
        if not entries:
            return cl
        nom = build_nominated(entries, cycle.builder.table)
        add = np.zeros(cl.requested.shape, np.float32)
        keep = nom.valid & (nom.node >= 0)
        np.add.at(add, nom.node[keep], nom.req[keep])
        return cl._replace(requested=cl.requested + jnp.asarray(add))

    # ------------------------------------------------------- candidate nodes

    def _wave_candidates(self, fwk, cycle: CycleContext,
                         pods: Sequence[api.Pod]) -> Dict[str, List[int]]:
        """reference: generic_scheduler.go:1041 nodesWherePreemptionMightHelp
        for the whole wave — every failed node that is not
        UnschedulableAndUnresolvable.  In-batch pods share ONE [B, N]
        verdict refresh; out-of-batch pods (direct/extender calls) share
        one grouped pass.  Host-filter failures count as resolvable
        failures too, so host verdicts are ANDed into feasibility here."""
        node_infos = cycle.node_infos
        n = len(node_infos)
        verd: Dict[str, tuple] = {}
        need_pass: List[api.Pod] = []
        for pod in pods:
            v = cycle.pod_verdicts(pod.uid)
            if v is None:
                # missing or stale (commits/evictions landed since): the
                # grouped wave-sized [Bw, N] pass below is never bigger
                # than a whole-batch refresh, and a 1-pod fallback wave
                # keeps its cheap [1, N]-bucket pass (pod_verdicts'
                # documented routing)
                need_pass.append(pod)
            else:
                verd[pod.uid] = v
        if need_pass:
            batch = self._pods_batch(need_pass, cycle)
            feas, unres = programs.filter_verdicts(cycle.cluster_now(),
                                                   batch, cycle.cfg)
            feas = np.asarray(feas)
            unres = np.asarray(unres)
            for i, pod in enumerate(need_pass):
                verd[pod.uid] = (feas[i], unres[i])
        out: Dict[str, List[int]] = {}
        for pod in pods:
            feasible, unresolvable = verd[pod.uid]
            feasible = np.array(feasible[:n])
            unresolvable = np.asarray(unresolvable[:n])
            if fwk.has_relevant_host_filters(pod):
                state = CycleState()
                for j, ni in enumerate(node_infos):
                    if feasible[j]:
                        st = fwk.run_filter_plugins(state, pod, ni)
                        if not st.is_success():
                            feasible[j] = False
            out[pod.uid] = [j for j, (f, u) in
                            enumerate(zip(feasible.tolist(),
                                          unresolvable.tolist()))
                            if not f and not u]
        return out

    # -------------------------------------------------------- victim search

    def _wave_round(self, fwk, cycle: CycleContext,
                    pods: Sequence[api.Pod], pdbs, deleted: set):
        """One contention round's what-if for every pending pod:
        candidates -> (fast wave | per-pod topology reprieve).  Returns
        (_FastWave or None, {slow pod uid: {node: Victims}})."""
        from .framework.types import pod_with_affinity

        cand = self._wave_candidates(fwk, cycle, pods)
        has_terms = cycle.has_filter_terms()
        fast: List[api.Pod] = []
        slow: List[api.Pod] = []
        for pod in pods:
            if not cand.get(pod.uid):
                continue
            # the wave kernel's static-verdict split is only sound when the
            # what-if provably cannot move a topology verdict (see
            # whatif_static_ok); term-carrying pods keep the exact per-pod
            # reprieve with pod_valid masking
            if (pod.spec.topology_spread_constraints
                    or pod_with_affinity(pod) or has_terms):
                slow.append(pod)
            else:
                fast.append(pod)
        fastw = self._fast_wave(cycle, fast, cand, pdbs, deleted) \
            if fast else None
        slow_entries = {}
        for pod in slow:
            cands = [(j, cycle.node_infos[j]) for j in cand[pod.uid]]
            slow_entries[pod.uid] = self._select_nodes_for_preemption(
                fwk, pod, cands, pdbs, cycle, deleted)
        return fastw, slow_entries

    def _prio_victim_prep(self, cycle: CycleContext, prio: int, pdbs,
                          deleted: set) -> Dict[int, Tuple[np.ndarray, int]]:
        """node row -> (victim index positions in reprieve order,
        n_pdb_violating) for a preemptor of priority `prio`.  Shared by
        every same-priority pod in the wave: the victim ORDER
        (PDB-violating first, then descending priority, :1004-1037)
        depends only on (priority, node), never on the preemptor's
        identity."""
        vi = cycle.victim_index()
        prep: Dict[int, Tuple[np.ndarray, int]] = {}
        for j, nv in vi.items():
            # prios is descending; evictable pods (< prio) are a suffix
            start = int(np.searchsorted(-nv.prios, -prio, side="right"))
            if start >= len(nv.prios):
                continue
            sel = np.arange(start, len(nv.prios))
            if deleted:
                keep = [int(k) for k in sel if nv.uids[k] not in deleted]
                if not keep:
                    continue
                sel = np.asarray(keep, np.int64)
            n_viol = 0
            if pdbs:
                # the per-PDB disruption budget consumes in SNAPSHOT order
                # (the serial path feeds ni.pods order, :1118) — feeding
                # the priority-sorted list would mark different victims as
                # violating and break wave == serial victim selection
                raw = sorted((int(k) for k in sel),
                             key=lambda k: int(nv.snap_pos[k]))
                violating, _ = filter_pods_with_pdb_violation(
                    [nv.pis[k].pod for k in raw], pdbs)
                vset = {p.uid for p in violating}
                lv = [int(k) for k in sel if nv.uids[k] in vset]
                lnv = [int(k) for k in sel if nv.uids[k] not in vset]
                sel = np.asarray(lv + lnv, np.int64)
                n_viol = len(lv)
            prep[j] = (sel, n_viol)
        return prep

    def _fast_wave(self, cycle: CycleContext, pods: List[api.Pod],
                   cand: Dict[str, List[int]], pdbs,
                   deleted: set) -> "_FastWave":
        """The wave kernel path: ONE [B, C, K] what-if for every term-free
        pending pod.  Host work is vectorized numpy — a compact
        per-(priority, node) victim table plus per-pod index rows; the
        [B, C, K, R] expansion happens on device (whatif_wave)."""
        import jax.numpy as jnp

        vi = cycle.victim_index()
        preps = {prio: self._prio_victim_prep(cycle, prio, pdbs, deleted)
                 for prio in {p.priority() for p in pods}}

        # per-pod candidate rows that actually carry victims, trimmed to
        # max_candidates by pickOneNode-style stats (cheapest kept)
        cand_lists: List[List[int]] = []
        for pod in pods:
            prep = preps[pod.priority()]
            rows = [j for j in cand[pod.uid] if j in prep]
            if len(rows) > self.max_candidates:
                def rank(j):
                    pr = vi[j].prios[prep[j][0]]
                    return (int(pr.max()), int(pr.sum()), len(pr))
                rows = sorted(rows, key=rank)[: self.max_candidates]
            cand_lists.append(rows)
        max_c = max((len(r) for r in cand_lists), default=0)
        if max_c == 0:
            return _FastWave.empty(pods)
        used = {(pod.priority(), j)
                for pod, rows in zip(pods, cand_lists) for j in rows}
        K = pow2_bucket(max(len(preps[prio][j][0]) for prio, j in used), 1)
        C = pow2_bucket(max_c, 1)
        R = int(cycle.cluster.requested.shape[1])

        # split along the pod axis if the device-side [B, C, K, R] gather
        # would blow the HBM budget (pathological candidate x victim
        # fan-out); chunks stay individually pow2-bucketed
        max_pods = max(1, self.max_wave_elements // max(C * K * R, 1))
        if len(pods) > max_pods:
            return _WaveUnion([
                self._fast_wave(cycle, pods[i:i + max_pods], cand, pdbs,
                                deleted)
                for i in range(0, len(pods), max_pods)])

        # compact victim table: one row per used (priority, node) — the
        # device gathers it out to [B, C, K, R], so the upload stays
        # O(S * K) however many same-priority preemptors share it
        order = sorted(used)
        S = pow2_bucket(len(order), 1)
        pos = {key: i for i, key in enumerate(order)}
        tab_req = np.zeros((S, K, R), np.float32)
        tab_valid = np.zeros((S, K), bool)
        tab_prio = np.full((S, K), -2**31, np.int64)
        tab_ts = np.zeros((S, K), np.float64)  # kubelint: ignore[numeric/f64] host-only pickOne tie-break timestamps; never device-bound
        tab_viol = np.zeros((S, K), bool)
        for (prio, j), i in pos.items():
            sel, n_viol = preps[prio][j]
            tab_req[i, :len(sel)] = vi[j].req[sel]
            tab_valid[i, :len(sel)] = True
            tab_prio[i, :len(sel)] = vi[j].prios[sel]
            tab_ts[i, :len(sel)] = vi[j].ts[sel]
            tab_viol[i, :n_viol] = True

        batch = self._pods_batch(pods, cycle)
        B = int(batch.valid.shape[0])     # pow2 pod-axis bucket
        cand_rows = np.full((B, C), -1, np.int32)
        cand_valid = np.zeros((B, C), bool)
        cand_idx = np.zeros((B, C), np.int32)
        for b, (pod, rows) in enumerate(zip(pods, cand_lists)):
            if not rows:
                continue
            nc = len(rows)
            prio = pod.priority()
            cand_rows[b, :nc] = np.asarray(rows, np.int32)
            cand_valid[b, :nc] = True
            cand_idx[b, :nc] = np.asarray([pos[(prio, j)] for j in rows],
                                          np.int32)

        # nominated-pod reservations per (pod, candidate): equal-or-greater
        # priority, self excluded (addNominatedPods, :594) — wave winners
        # of earlier rounds are in the queue nominator already
        nom_add = None
        node_row = {ni.node_name: j
                    for j, ni in enumerate(cycle.node_infos)}
        table = cycle.builder.table
        for p, nn in self.sched.queue.all_nominated():
            row = node_row.get(nn)
            if row is None:
                continue
            vec = _pod_channels(PodInfo(p), table, R)
            hit = cand_rows == row                       # [B, C]
            for b, pod in enumerate(pods):
                if p.uid == pod.uid or p.priority() < pod.priority():
                    continue
                if nom_add is None:
                    nom_add = np.zeros((B, C, R), np.float32)
                nom_add[b][hit[b]] += vec
        # jnp.zeros allocates device-side — the no-nominations common case
        # uploads nothing and keeps the jit signature stable
        nom_dev = (jnp.zeros((B, C, R), jnp.float32) if nom_add is None
                   else jnp.asarray(nom_add))

        # the droppable topology filters are gone for every fast pod by
        # construction (that is what made them fast)
        cfg_w = cycle.cfg._replace(filters=tuple(
            f for f in cycle.cfg.filters
            if f not in ("PodTopologySpread", "InterPodAffinity")))
        cluster = cycle.cluster_now()
        static_ok = programs.whatif_static_ok(cluster, batch, cfg_w)
        # flight_span attaches under the scheduler's open preemption-wave
        # span (utils/trace.py) — no-op when the recorder is disarmed
        with flight_span("whatif-readback", pods=B) as sp:
            # perf_counter, not time.time(): the wait is a DURATION, and
            # an NTP step mid-wave used to corrupt it (negative or wildly
            # inflated device_wait_s in the span args)
            t_dev = time.perf_counter()
            packed = np.asarray(programs.whatif_wave(
                cluster, static_ok, jnp.asarray(np.asarray(batch.req)),
                jnp.asarray(cand_rows), jnp.asarray(cand_valid), nom_dev,
                jnp.asarray(tab_req), jnp.asarray(tab_valid),
                jnp.asarray(cand_idx)))   # ONE readback for the whole wave
            if sp is not None:
                # wave device-wait attribution (the what-if dispatch +
                # transfer is the wave's only device sync)
                sp.args["device_wait_s"] = round(
                    time.perf_counter() - t_dev, 6)

        # pickOneNode metrics, vectorized over the whole [B, C, K] block
        # (generic_scheduler.go:729 criteria 1-5; criterion 6 = first in
        # candidate order, the argmin tie-break in _FastWave.resolve)
        evicted = (tab_valid[cand_idx] & cand_valid[:, :, None]
                   & ~packed[:, :, 1:])                      # [B, C, K]
        prio_g = tab_prio[cand_idx]
        fits = packed[:, :, 0] & cand_valid
        m1 = (evicted & tab_viol[cand_idx]).sum(axis=2)
        m2 = np.where(evicted, prio_g, -2**31).max(axis=2)
        m3 = np.where(evicted, prio_g, 0).sum(axis=2)
        m4 = evicted.sum(axis=2)
        # latest start time of the highest-priority victim: argmax takes
        # the FIRST max like the serial max() — matching reprieve order
        top = np.argmax(np.where(evicted, prio_g, -2**31), axis=2)
        m5 = -np.take_along_axis(tab_ts[cand_idx], top[:, :, None],
                                 axis=2)[:, :, 0]
        m5 = np.where(m4 > 0, m5, 0.0)
        return _FastWave(cycle=cycle, pods=pods, cand_lists=cand_lists,
                         preps=preps, vi=vi, evicted=evicted, fits=fits,
                         metrics=(m1, m2, m3, m4, m5))


    def _select_nodes_for_preemption(self, fwk, pod: api.Pod,
                                     candidates, pdbs,
                                     cycle: CycleContext,
                                     deleted: set = frozenset()
                                     ) -> Dict[str, Victims]:
        """reference: generic_scheduler.go:858 selectNodesForPreemption —
        the parallel what-if for ONE topology-term-carrying pod, batched
        over every candidate (see _whatif_reprieve).

        The what-if's cfg drops topology filters the preemptor provably
        cannot trip: PodTopologySpread constrains only pods WITH
        constraints, and InterPodAffinity is droppable when the pod has no
        affinity terms AND no existing pod carries a filter term (removing
        victims can then never change the verdict).  Without this, every
        candidate paid the [1, P] x [P, N] same-pair matmuls — at
        5000 nodes x 20k pods the 2048-candidate what-if cost seconds per
        preemptor for workloads with no topology terms at all."""
        import jax.numpy as jnp
        from .framework.types import pod_with_affinity

        cfg_w = cycle.cfg
        drop = []
        if not pod.spec.topology_spread_constraints:
            drop.append("PodTopologySpread")
        if not pod_with_affinity(pod) and not cycle.has_filter_terms():
            drop.append("InterPodAffinity")
        if drop:
            cfg_w = cfg_w._replace(filters=tuple(
                f for f in cfg_w.filters if f not in drop))

        prio = pod.priority()
        table = cycle.builder.table
        R = cycle.cluster.requested.shape[1]
        P = cycle.cluster.pod_valid.shape[0]

        # per-candidate victim lists in reprieve order: PDB-violating first,
        # each group by descending priority (:1004-1037)
        entries = []  # (row, ordered victims [PodInfo], n_violating)
        pod_rows = cycle.pod_row_map()
        for row, ni in candidates:
            lower = [pi for pi in ni.pods
                     if pi.pod.priority() < prio
                     and pi.pod.uid not in deleted]
            if not lower:
                continue
            violating, non_violating = filter_pods_with_pdb_violation(
                [pi.pod for pi in lower], pdbs)
            vset = {p.uid for p in violating}
            lv = sorted((pi for pi in lower if pi.pod.uid in vset),
                        key=lambda pi: -pi.pod.priority())
            lnv = sorted((pi for pi in lower if pi.pod.uid not in vset),
                         key=lambda pi: -pi.pod.priority())
            entries.append((row, lv + lnv, len(lv)))
        if not entries:
            return {}
        if len(entries) > self.max_candidates:
            # memory cap: keep the candidates cheapest by pickOneNode-style
            # stats (lowest max victim priority, then sum, then count)
            def rank(e):
                vs = e[1]
                return (max(pi.pod.priority() for pi in vs),
                        sum(pi.pod.priority() for pi in vs), len(vs))
            entries = sorted(entries, key=rank)[: self.max_candidates]

        C = pow2_bucket(len(entries), 1)
        K = pow2_bucket(max(len(e[1]) for e in entries), 1)
        cand_rows = np.zeros((C,), np.int32)
        rm_valid = np.broadcast_to(
            np.asarray(cycle.cluster.pod_valid), (C, P)).copy()
        rm_req = np.zeros((C, R), np.float32)
        rm_nz = np.zeros((C, 2), np.float32)
        vic_row = np.full((C, K), -1, np.int32)
        vic_req = np.zeros((C, K, R), np.float32)
        vic_nz = np.zeros((C, K, 2), np.float32)
        for c, (row, victims, _nv) in enumerate(entries):
            cand_rows[c] = row
            for k, pi in enumerate(victims):
                prow = pod_rows.get(pi.pod.uid, -1)
                if prow >= 0:
                    rm_valid[c, prow] = False
                vic_row[c, k] = prow
                vr = _pod_channels(pi, table, R)
                vic_req[c, k] = vr
                vic_nz[c, k, 0] = pi.non_zero_cpu
                vic_nz[c, k, 1] = pi.non_zero_mem / MIB
                rm_req[c] += vr
                rm_nz[c] += vic_nz[c, k]
        # pad rows: candidate 0's row with no removals (fits0 false unless
        # genuinely feasible; padded candidates are dropped below)
        for c in range(len(entries), C):
            cand_rows[c] = entries[0][0]

        batch1 = self._pod_batch1(pod, cycle)
        fits0, reprieved = _whatif_reprieve(
            self._cluster_with_nominated(pod, cycle), batch1, cfg_w,
            jnp.asarray(cand_rows), jnp.asarray(rm_valid),
            jnp.asarray(rm_req), jnp.asarray(rm_nz), jnp.asarray(vic_row),
            jnp.asarray(vic_req), jnp.asarray(vic_nz))
        fits0 = np.asarray(fits0)
        reprieved = np.asarray(reprieved)  # [K, C]

        out: Dict[str, Victims] = {}
        for c, (row, victims, n_violating) in enumerate(entries):
            if not fits0[c]:
                continue
            final = [victims[k].pod for k in range(len(victims))
                     if not reprieved[k, c]]
            num_viol = sum(1 for k in range(min(n_violating, len(victims)))
                           if not reprieved[k, c])
            ni = cycle.node_infos[row]
            if not self._host_filters_pass(fwk, pod, ni,
                                           {p.uid for p in final}):
                continue
            out[ni.node_name] = Victims(pods=final,
                                        num_pdb_violations=num_viol)
        return out

    def _host_filters_pass(self, fwk, pod: api.Pod, ni: NodeInfo,
                           removed_uids: set) -> bool:
        if not fwk.has_relevant_host_filters(pod):
            return True
        sim_ni = ni.clone()
        for pi in list(sim_ni.pods):
            if pi.pod.uid in removed_uids:
                sim_ni.remove_pod(pi.pod)
        st = fwk.run_filter_plugins(CycleState(), pod, sim_ni)
        return st.is_success()

    # ------------------------------------------------------------- extenders

    def _process_with_extenders(self, pod: api.Pod,
                                node_victims: Dict[str, Victims]
                                ) -> Dict[str, Victims]:
        """reference: generic_scheduler.go:317 processPreemptionWithExtenders
        + core/extender.go:317 ProcessPreemption."""
        if not node_victims:
            return node_victims
        for ext in self.sched.extenders:
            if not (ext.supports_preemption() and ext.is_interested(pod)):
                continue
            try:
                node_victims = ext.process_preemption(pod, node_victims)
            except Exception:
                if getattr(ext, "ignorable", False):
                    continue
                return {}
            if not node_victims:
                return {}
        return node_victims


class _FastWave:
    """One round's wave what-if results plus lazy contention resolution.

    resolve() reproduces pick_one_node_for_preemption's lexicographic
    tie-break over vectorized numpy metric arrays — criteria 1-5 as
    argmin filters, criterion 6 (first remaining) as candidate order —
    and materializes a Victims list only for the winner.  Host-filter
    validation runs on the winner and, on failure, bans the node and
    re-resolves (equivalent to the eager path's pre-pick entry drop)."""

    def __init__(self, cycle, pods, cand_lists, preps, vi, evicted, fits,
                 metrics):
        self.cycle = cycle
        self.pods = pods
        self.cand_lists = cand_lists
        self.preps = preps
        self.vi = vi
        self.evicted = evicted          # [B, C, K] bool
        self.fits = fits                # [B, C] bool
        self.metrics = metrics          # 5 x [B, C]
        self.index = {pod.uid: b for b, pod in enumerate(pods)}
        self.names = [[cycle.node_infos[j].node_name for j in rows]
                      for rows in cand_lists]

    @classmethod
    def empty(cls, pods):
        z = np.zeros((len(pods), 0), np.int64)
        return cls(cycle=None, pods=pods, cand_lists=[[] for _ in pods],
                   preps={}, vi={}, evicted=np.zeros((len(pods), 0, 0),
                                                     bool),
                   fits=z.astype(bool), metrics=(z, z, z, z, z))

    def _victims(self, pod, b: int, c: int) -> Victims:
        j = self.cand_lists[b][c]
        sel, n_viol = self.preps[pod.priority()][j]
        ev = self.evicted[b, c, :len(sel)].tolist()
        final = [self.vi[j].pis[int(k)].pod
                 for t, k in enumerate(sel) if ev[t]]
        num_viol = sum(1 for t in range(min(n_viol, len(sel))) if ev[t])
        return Victims(pods=final, num_pdb_violations=num_viol)

    def _pick(self, b: int, skip: set) -> Optional[int]:
        names = self.names[b]
        nc = len(names)
        if nc == 0:
            return None
        ok = self.fits[b, :nc].copy()
        if skip:
            ok &= np.fromiter((n not in skip for n in names), bool, nc)
        idx = np.flatnonzero(ok)
        for m in self.metrics:
            if idx.size <= 1:
                break
            vals = m[b, idx]
            idx = idx[vals == vals.min()]
        return int(idx[0]) if idx.size else None

    def resolve(self, fwk, preemptor, pod, b: int, claimed: set):
        """(node, victims, had_claimed) — had_claimed: some feasible entry
        was lost to a same-round claim (the re-wave trigger)."""
        names = self.names[b]
        had_claimed = bool(claimed) and any(
            n in claimed for n, f in zip(names, self.fits[b].tolist()) if f)
        banned = set(claimed)
        while True:
            c = self._pick(b, banned)
            if c is None:
                return None, None, had_claimed
            victims = self._victims(pod, b, c)
            j = self.cand_lists[b][c]
            if preemptor._host_filters_pass(
                    fwk, pod, self.cycle.node_infos[j],
                    {p.uid for p in victims.pods}):
                return names[c], victims, had_claimed
            banned.add(names[c])

    def entries_dict(self, fwk, preemptor, pod,
                     b: int) -> Dict[str, Victims]:
        """Eager node_victims dict (extender path only — extenders inspect
        the full map, reference ProcessPreemption)."""
        out: Dict[str, Victims] = {}
        for c, name in enumerate(self.names[b]):
            if not self.fits[b, c]:
                continue
            victims = self._victims(pod, b, c)
            j = self.cand_lists[b][c]
            if not preemptor._host_filters_pass(
                    fwk, pod, self.cycle.node_infos[j],
                    {p.uid for p in victims.pods}):
                continue
            out[name] = victims
        return out


class _WaveUnion:
    """Routes per-pod wave handles across HBM-budget chunks of one round
    (the opaque b handle becomes (chunk, b))."""

    def __init__(self, waves):
        self.waves = waves
        self.index = {uid: (w, b) for w in waves
                      for uid, b in w.index.items()}

    def resolve(self, fwk, preemptor, pod, key, claimed):
        w, b = key
        return w.resolve(fwk, preemptor, pod, b, claimed)

    def entries_dict(self, fwk, preemptor, pod, key):
        w, b = key
        return w.entries_dict(fwk, preemptor, pod, b)


# ---------------------------------------------------------------------------
# pure functions (host)


def filter_pods_with_pdb_violation(pods: List[api.Pod],
                                   pdbs) -> Tuple[List[api.Pod], List[api.Pod]]:
    """reference: generic_scheduler.go:1118 filterPodsWithPDBViolation."""
    violating, non_violating = [], []
    remaining = {id(pdb): pdb.disruptions_allowed for pdb in pdbs}
    for p in pods:
        hit = False
        for pdb in pdbs:
            if pdb.metadata.namespace != p.namespace:
                continue
            if pdb.selector is not None and pdb.selector.matches(
                    p.metadata.labels):
                if remaining[id(pdb)] <= 0:
                    hit = True
                else:
                    remaining[id(pdb)] -= 1
        (violating if hit else non_violating).append(p)
    return violating, non_violating


def pick_one_node_for_preemption(node_victims: Dict[str, Victims]) -> Optional[str]:
    """reference: generic_scheduler.go:729 — lexicographic tie-break:
    1. fewest PDB violations
    2. lowest highest-victim-priority
    3. lowest sum of victim priorities
    4. fewest victims
    5. latest earliest start time of highest-priority victim
    6. first in iteration order (reference returns the first remaining)."""
    if not node_victims:
        return None
    nodes = list(node_victims)

    def metric(fns):
        nonlocal nodes
        vals = {n: fns(node_victims[n]) for n in nodes}
        best = min(vals.values())
        nodes = [n for n in nodes if vals[n] == best]

    metric(lambda v: v.num_pdb_violations)
    if len(nodes) == 1:
        return nodes[0]
    metric(lambda v: max((p.priority() for p in v.pods), default=-2**31))
    if len(nodes) == 1:
        return nodes[0]
    metric(lambda v: sum(p.priority() for p in v.pods))
    if len(nodes) == 1:
        return nodes[0]
    metric(lambda v: len(v.pods))
    if len(nodes) == 1:
        return nodes[0]
    # latest start time of the highest-priority victim (max => min of -ts)
    def neg_latest_start(v: Victims):
        if not v.pods:
            return 0.0
        top = max(v.pods, key=lambda p: p.priority())
        return -top.metadata.creation_timestamp
    metric(neg_latest_start)
    return nodes[0]
