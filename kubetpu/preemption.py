"""Preemption: batched what-if victim selection.

reference: pkg/scheduler/core/generic_scheduler.go — Preempt :252,
podEligibleToPreemptOthers :1063, nodesWherePreemptionMightHelp :1041,
selectNodesForPreemption :858, selectVictimsOnNode :949 (clone + remove
lower-priority pods + re-run filters + reprieve by PDB then priority),
processPreemptionWithExtenders :317, pickOneNodeForPreemption :729
(6-criteria lexicographic tie-break); invoked from scheduler.go:391 preempt.

TPU shape of the what-if: the reference clones one NodeInfo per candidate
and serially re-runs all filter plugins per victim add-back — an
O(candidates x victims) host loop.  Here the candidate axis is vmapped:
every candidate's what-if state is the shared cycle snapshot plus a
per-candidate delta (its own victims' pod rows masked out, their resources
subtracted from its own node row), and ONE jitted pass answers "does the
pod now fit" for ALL candidates at once.  The reprieve loop becomes a
lax.scan over add-back depth: step k tries every candidate's k-th victim
(PDB-violating first, then by descending priority — :1004-1037)
simultaneously, so total device passes per preemption = reprieve depth + 1,
independent of the candidate count.

The cycle's snapshot tensors are reused (reference Preempt reuses the
Schedule call's nodeInfoSnapshot); nothing is re-tensorized per failed pod.

Host-filter deviation: volume-type (host) filters are validated against the
final victim-adjusted NodeInfo instead of inside every reprieve step — the
device reprieve covers all tensor filters; a host filter can therefore only
differ from the reference on a mid-reprieve add-back whose feasibility
flips on volumes alone.
"""

from __future__ import annotations

import functools
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import numpy as np

from .api import types as api
from .framework.interface import CycleState
from .framework.types import NodeInfo, PodInfo
from .models import programs
from .models.batch import PodBatchBuilder
from .state.tensors import MIB, CH_PODS, SnapshotBuilder
from .utils.intern import pow2_bucket


class Victims:
    __slots__ = ("pods", "num_pdb_violations")

    def __init__(self, pods: List[api.Pod], num_pdb_violations: int):
        self.pods = pods
        self.num_pdb_violations = num_pdb_violations


class CycleContext:
    """Per-cycle tensors the scheduler shares with preemption (reference:
    Preempt runs against the same g.nodeInfoSnapshot as Schedule).  Also
    caches per-pod feasibility rows so N failed pods cost ONE candidates
    pass, not N."""

    def __init__(self, builder: SnapshotBuilder, cluster, cfg,
                 node_infos: Sequence[NodeInfo], batch=None,
                 row_of: Optional[Dict[str, int]] = None,
                 feasible=None, unresolvable=None):
        self.builder = builder
        self.cluster = cluster
        self.cfg = cfg
        self.node_infos = node_infos
        self.batch = batch           # the cycle's PodBatch (all live pods)
        self.row_of = row_of or {}   # pod uid -> batch row
        self.feasible = feasible     # [B, N] np.ndarray or None
        self.unresolvable = unresolvable
        # same-cycle committed placements, overlaid before any what-if: the
        # reference's reused nodeInfoSnapshot serves exactly ONE pod per
        # cycle; with B pods per cycle a pod failing late in the batch must
        # see the capacity already claimed by earlier commits or preemption
        # overestimates free space and deletes victims for nothing
        self.commit_req = None       # [N, R] np — committed request channels
        self.commit_nz = None        # [N, 2] np
        self.commit_ports = None     # [N, P] np bool — committed host ports
        self.commits = 0
        self._verdict_commits = 0
        self._cluster_cache = None   # (commits, overlaid cluster)
        self._lazy = None            # (feasible_dev, unresolvable_dev)
        self.pod_rows = None         # uid -> existing-pod tensor row (set
                                     # by the scheduler; required when the
                                     # cluster is CHAINED and rows no
                                     # longer follow node_infos order)
        self._has_filter_terms = None  # lazy: any valid existing
                                       # anti-affinity term in the cluster

    def has_filter_terms(self) -> bool:
        """Does the cluster carry ANY valid existing-pod required
        anti-affinity term?  (One tiny readback, cached per cycle.)  When
        False, removing victims cannot change the InterPodAffinity verdict
        of a term-less preemptor, so the what-if may drop that filter."""
        if self._has_filter_terms is None:
            self._has_filter_terms = bool(
                np.asarray(self.cluster.filter_terms.valid).any())
        return self._has_filter_terms

    def set_lazy_verdicts(self, feasible_dev, unresolvable_dev) -> None:
        """Share DEVICE verdict arrays without forcing a transfer: they
        materialize only if a preemption attempt actually reads them with
        no commits in between (otherwise a refresh supersedes them and the
        multi-MB device->host copy never happens)."""
        self._lazy = (feasible_dev, unresolvable_dev)

    def note_commit(self, row: int, node_row: int) -> None:
        """Record a committed batch placement (batch row -> node row)."""
        if self.batch is None:
            return
        if self.commit_req is None:
            shape = self.cluster.requested.shape
            self.commit_req = np.zeros(shape, np.float32)
            self.commit_nz = np.zeros((shape[0], 2), np.float32)
            self.commit_ports = np.zeros(
                (shape[0], self.batch.ports_asnode_hot.shape[1]), bool)
        self.commit_req[node_row] += np.asarray(self.batch.req[row])
        self.commit_nz[node_row] += np.asarray(self.batch.nonzero_req[row])
        self.commit_ports[node_row] |= (
            np.asarray(self.batch.ports_asnode_hot[row]) > 0.5)
        self.commits += 1

    def cluster_now(self):
        """The cycle's cluster tensors with committed placements overlaid
        (resource/pod-count channels and host ports; committed pods'
        topology terms are not overlaid — a bounded deviation, matching the
        nominated-pods overlay's scope in the reference,
        generic_scheduler.go:541-545)."""
        if self.commits == 0:
            return self.cluster
        if (self._cluster_cache is not None
                and self._cluster_cache[0] == self.commits):
            return self._cluster_cache[1]
        import jax.numpy as jnp
        cl = self.cluster._replace(
            requested=self.cluster.requested + jnp.asarray(self.commit_req),
            nonzero_requested=(self.cluster.nonzero_requested
                               + jnp.asarray(self.commit_nz)),
            ports=self.cluster.ports | jnp.asarray(self.commit_ports))
        self._cluster_cache = (self.commits, cl)
        return cl

    def pod_verdicts(self, pod_uid: str):
        """(feasible_row, unresolvable_row) for a cycle pod, computing the
        whole-batch filter pass lazily on first use (one device call shared
        by every preemption attempt this cycle).  Verdicts taken before the
        latest commit are STALE — a gang-mode pod that lost purely to
        intra-batch contention has round-0 feasibility on nodes that are now
        full, which would exclude exactly the cheapest preemption
        candidates; returning None routes the caller to its single-pod
        [1, N] pass against cluster_now(), far cheaper than re-running the
        whole [B, N] batch per failing pod."""
        row = self.row_of.get(pod_uid)
        if row is None:
            return None
        self._materialize_lazy()
        if self.feasible is not None and self._verdict_commits != self.commits:
            return None
        if self.feasible is None:
            if self.batch is None:
                return None
            self.refresh_verdicts()
        return self.feasible[row], self.unresolvable[row]

    def _materialize_lazy(self) -> None:
        """Pull the auction's device verdict arrays to host IF they are
        still current (no commits since) and nothing fresher exists."""
        if self.feasible is None and self._lazy is not None \
                and self.commits == 0:
            self.feasible = np.asarray(self._lazy[0])
            self.unresolvable = np.asarray(self._lazy[1])

    def refresh_verdicts(self) -> None:
        """One whole-batch filter pass against the CURRENT committed state,
        shared by every preemption attempt that follows.  The scheduler
        calls this once after the commit loop so N failed pods cost one
        [B, N] pass, not N single-pod passes."""
        feasible, unresolvable = programs.filter_verdicts(
            self.cluster_now(), self.batch, self.cfg)
        self.feasible = np.asarray(feasible)
        self.unresolvable = np.asarray(unresolvable)
        self._verdict_commits = self.commits

    def min_pod_priority(self):
        """Lowest priority among all existing pods (lazily computed once
        per cycle), or None when the cluster has no pods.  A preemptor
        whose priority is <= this can never find a victim, so preemption
        short-circuits without any device pass (the reference reaches the
        same conclusion inside selectVictimsOnNode, one candidate at a
        time)."""
        if not hasattr(self, "_min_prio"):
            prios = [pi.pod.priority() for ni in self.node_infos
                     for pi in ni.pods]
            self._min_prio = min(prios) if prios else None
        return self._min_prio


@functools.partial(jax.jit, static_argnames=("cfg",))
def _whatif_reprieve(cluster, batch1, cfg, cand_rows, rm_valid, rm_req,
                     rm_nz, vic_row, vic_req, vic_nz):
    """Batched selectVictimsOnNode (generic_scheduler.go:949).

    cand_rows [C]        candidate node rows
    rm_valid  [C, P]     pod_valid with ALL of each candidate's lower-priority
                         pods masked out
    rm_req    [C, R]     summed resources of those pods (per own node row)
    rm_nz     [C, 2]     their non-zero-request sums
    vic_row   [C, K]     victim pod rows in reprieve order (-1 pad)
    vic_req   [C, K, R]  per-victim resources
    vic_nz    [C, K, 2]

    Returns (fits0 [C] — pod fits with all victims removed,
             reprieved [K, C] — victim k stayed on the node)."""
    import jax.numpy as jnp

    from .models.batch import densify_for
    batch1 = densify_for(cluster, batch1)
    C = cand_rows.shape[0]
    K = vic_row.shape[1]
    base_req = cluster.requested
    base_nz = cluster.nonzero_requested

    def one(pod_valid, dreq, dnz, row):
        cl = cluster._replace(
            pod_valid=pod_valid,
            requested=base_req.at[row].add(-dreq),
            nonzero_requested=base_nz.at[row].add(-dnz))
        feas, _, _ = programs.run_filters(cl, batch1, cfg)
        return feas[0]  # [N]

    vfilter = jax.vmap(one, in_axes=(0, 0, 0, 0))

    def verdicts(pod_valid, dreq, dnz):
        feas = vfilter(pod_valid, dreq, dnz, cand_rows)       # [C, N]
        return jnp.take_along_axis(feas, cand_rows[:, None], 1)[:, 0]

    fits0 = verdicts(rm_valid, rm_req, rm_nz)

    def step(carry, k):
        pod_valid, dreq, dnz, ok = carry
        row = vic_row[:, k]                                   # [C]
        exists = (row >= 0) & ok
        e = exists.astype(jnp.float32)
        try_valid = pod_valid.at[jnp.arange(C), jnp.clip(row, 0)].max(exists)
        try_dreq = dreq - vic_req[:, k] * e[:, None]
        try_dnz = dnz - vic_nz[:, k] * e[:, None]
        fit = verdicts(try_valid, try_dreq, try_dnz) & exists
        keep = fit[:, None]
        pod_valid = jnp.where(keep, try_valid, pod_valid)
        dreq = jnp.where(keep, try_dreq, dreq)
        dnz = jnp.where(keep, try_dnz, dnz)
        return (pod_valid, dreq, dnz, ok), fit

    (_, _, _, _), reprieved = jax.lax.scan(
        step, (rm_valid, rm_req, rm_nz, fits0), jnp.arange(K))
    return fits0, reprieved


class Preemptor:
    def __init__(self, scheduler, max_candidates: int = 2048):
        self.sched = scheduler
        # memory bound on the vmapped candidate axis, NOT the reference's
        # behavior — when exceeded, candidates are pre-ranked and trimmed
        self.max_candidates = max_candidates

    # ------------------------------------------------------------------ entry

    def preempt(self, fwk, state: CycleState, pod: api.Pod,
                cycle: Optional[CycleContext] = None) -> Optional[str]:
        """reference: scheduler.go:391 + generic_scheduler.go:252 Preempt.
        Returns the nominated node name, or None."""
        sched = self.sched
        pod = sched.store.get_pod(pod.namespace, pod.metadata.name) or pod
        if not self._eligible(pod):
            return None
        if cycle is None:
            cycle = self._build_cycle(fwk, pod)
        node_infos = cycle.node_infos
        if not node_infos:
            return None
        min_prio = cycle.min_pod_priority()
        if min_prio is None or pod.priority() <= min_prio:
            # nothing anywhere is evictable by this pod — skip the whole
            # candidates/what-if machinery
            return None

        cand = self._nodes_where_preemption_might_help(fwk, pod, cycle)
        if not cand:
            return None
        pdbs = sched.store.list("PodDisruptionBudget")
        node_victims = self._select_nodes_for_preemption(fwk, pod, cand,
                                                         pdbs, cycle)
        node_victims = self._process_with_extenders(pod, node_victims)
        if not node_victims:
            return None
        best = pick_one_node_for_preemption(node_victims)
        if best is None:
            return None

        victims = node_victims[best]
        for victim in victims.pods:
            # delete victims via the API (reference: scheduler.go:403-415)
            try:
                sched.store.delete(victim)
            except Exception:
                pass
            if sched.recorder:
                sched.recorder.event(victim, "Normal", "Preempted",
                                     f"by {pod.namespace}/{pod.metadata.name} "
                                     f"on node {best}")
        # reject lower-priority waiting (Permit) pods on the node
        def maybe_reject(wp):
            if (wp.pod.priority() < pod.priority()):
                wp.reject("preempted")
        fwk.iterate_over_waiting_pods(maybe_reject)
        # clear nomination of lower-priority pods nominated to this node
        for np_ in sched.queue.nominated_pods_for_node(best):
            if np_.priority() < pod.priority():
                sched.queue.delete_nominated_pod_if_exists(np_)
        sched.queue.add_nominated_pod(pod, best)
        return best

    def _eligible(self, pod: api.Pod) -> bool:
        """reference: generic_scheduler.go:1063 podEligibleToPreemptOthers —
        if the pod already nominated a node and a lower-priority pod there
        is terminating, wait instead of preempting again."""
        nominated = pod.status.nominated_node_name
        if not nominated:
            return True
        ni = self.sched.snapshot.get(nominated)
        if ni is None:
            return True
        for pi in ni.pods:
            if (pi.pod.metadata.deletion_timestamp is not None
                    and pi.pod.priority() < pod.priority()):
                return False
        return True

    # ------------------------------------------------------------ cycle state

    def _build_cycle(self, fwk, pod: api.Pod) -> CycleContext:
        """Fallback when no cycle tensors were handed over (direct calls,
        extender path)."""
        sched = self.sched
        sched.cache.update_snapshot(sched.snapshot)
        node_infos = list(sched.snapshot.node_info_list)
        builder = SnapshotBuilder(
            hard_pod_affinity_weight=fwk.hard_pod_affinity_weight)
        builder.intern_pending([PodInfo(pod)])
        cluster = builder.build(node_infos).to_device()
        cfg = programs.ProgramConfig(
            filters=fwk.tensor_filters, scores=fwk.tensor_scores,
            hostname_topokey=max(
                builder.table.topokey.get(api.LABEL_HOSTNAME), 0),
            plugin_args=fwk.tensor_plugin_args(builder.table))
        return CycleContext(builder=builder, cluster=cluster, cfg=cfg,
                            node_infos=node_infos)

    def _pod_batch1(self, pod: api.Pod, cycle: CycleContext):
        import jax
        pb = PodBatchBuilder(cycle.builder.table)
        sel = self.sched.store.default_spread_selector(pod)
        return jax.tree.map(np.asarray,
                            pb.build([PodInfo(pod)], spread_selectors=[sel]))

    def _cluster_with_nominated(self, pod: api.Pod, cycle: CycleContext):
        """cluster_now plus equal/higher-priority nominated pods' resources
        on their nominated rows — the preemption simulation must respect
        capacity other preemptors already reserved (reference:
        addNominatedPods inside fitsOnNode, generic_scheduler.go:594)."""
        import jax.numpy as jnp
        from .models.batch import build_nominated
        cl = cycle.cluster_now()
        prio = pod.priority()
        node_row = {ni.node_name: j
                    for j, ni in enumerate(cycle.node_infos)}
        entries = []
        for p, nn in self.sched.queue.all_nominated():
            if p.uid == pod.uid or p.priority() < prio:
                continue
            row = node_row.get(nn)
            if row is None:
                continue
            entries.append((PodInfo(p), row))
        if not entries:
            return cl
        nom = build_nominated(entries, cycle.builder.table)
        add = np.zeros(cl.requested.shape, np.float32)
        keep = nom.valid & (nom.node >= 0)
        np.add.at(add, nom.node[keep], nom.req[keep])
        return cl._replace(requested=cl.requested + jnp.asarray(add))

    # ------------------------------------------------------- candidate nodes

    def _nodes_where_preemption_might_help(self, fwk, pod: api.Pod,
                                           cycle: CycleContext):
        """reference: generic_scheduler.go:1041 — every failed node that is
        not UnschedulableAndUnresolvable.  Host-filter failures count as
        resolvable failures too (nodesWherePreemptionMightHelp considers
        them), so host verdicts are ANDed into feasibility here."""
        node_infos = cycle.node_infos
        verdicts = cycle.pod_verdicts(pod.uid)
        if verdicts is None:
            batch1 = self._pod_batch1(pod, cycle)
            feas1, unres1 = programs.filter_verdicts(cycle.cluster_now(),
                                                     batch1, cycle.cfg)
            feasible = np.asarray(feas1)[0]
            unresolvable = np.asarray(unres1)[0]
        else:
            feasible, unresolvable = verdicts
        feasible = np.array(feasible[:len(node_infos)])
        unresolvable = unresolvable[:len(node_infos)]
        if fwk.has_relevant_host_filters(pod):
            state = CycleState()
            for j, ni in enumerate(node_infos):
                if feasible[j]:
                    st = fwk.run_filter_plugins(state, pod, ni)
                    if not st.is_success():
                        feasible[j] = False
        self._batch1 = None  # built lazily when victims exist
        return [(j, ni) for j, (ni, f, u) in
                enumerate(zip(node_infos, feasible, unresolvable))
                if not f and not u]

    # -------------------------------------------------------- victim search

    def _select_nodes_for_preemption(self, fwk, pod: api.Pod,
                                     candidates, pdbs,
                                     cycle: CycleContext) -> Dict[str, Victims]:
        """reference: generic_scheduler.go:858 selectNodesForPreemption —
        the parallel what-if, here ONE batched device program over every
        candidate (see _whatif_reprieve).

        The what-if's cfg drops topology filters the preemptor provably
        cannot trip: PodTopologySpread constrains only pods WITH
        constraints, and InterPodAffinity is droppable when the pod has no
        affinity terms AND no existing pod carries a filter term (removing
        victims can then never change the verdict).  Without this, every
        candidate paid the [1, P] x [P, N] same-pair matmuls — at
        5000 nodes x 20k pods the 2048-candidate what-if cost seconds per
        preemptor for workloads with no topology terms at all."""
        import jax.numpy as jnp
        from .framework.types import pod_with_affinity

        cfg_w = cycle.cfg
        drop = []
        if not pod.spec.topology_spread_constraints:
            drop.append("PodTopologySpread")
        if not pod_with_affinity(pod) and not cycle.has_filter_terms():
            drop.append("InterPodAffinity")
        if drop:
            cfg_w = cfg_w._replace(filters=tuple(
                f for f in cfg_w.filters if f not in drop))

        prio = pod.priority()
        table = cycle.builder.table
        R = cycle.cluster.requested.shape[1]
        P = cycle.cluster.pod_valid.shape[0]

        # per-candidate victim lists in reprieve order: PDB-violating first,
        # each group by descending priority (:1004-1037)
        entries = []  # (row, ordered victims [PodInfo], n_violating)
        pod_rows = self._pod_rows(cycle)
        for row, ni in candidates:
            lower = [pi for pi in ni.pods if pi.pod.priority() < prio]
            if not lower:
                continue
            violating, non_violating = filter_pods_with_pdb_violation(
                [pi.pod for pi in lower], pdbs)
            vset = {p.uid for p in violating}
            lv = sorted((pi for pi in lower if pi.pod.uid in vset),
                        key=lambda pi: -pi.pod.priority())
            lnv = sorted((pi for pi in lower if pi.pod.uid not in vset),
                         key=lambda pi: -pi.pod.priority())
            entries.append((row, lv + lnv, len(lv)))
        if not entries:
            return {}
        if len(entries) > self.max_candidates:
            # memory cap: keep the candidates cheapest by pickOneNode-style
            # stats (lowest max victim priority, then sum, then count)
            def rank(e):
                vs = e[1]
                return (max(pi.pod.priority() for pi in vs),
                        sum(pi.pod.priority() for pi in vs), len(vs))
            entries = sorted(entries, key=rank)[: self.max_candidates]

        C = pow2_bucket(len(entries), 1)
        K = pow2_bucket(max(len(e[1]) for e in entries), 1)
        cand_rows = np.zeros((C,), np.int32)
        rm_valid = np.broadcast_to(
            np.asarray(cycle.cluster.pod_valid), (C, P)).copy()
        rm_req = np.zeros((C, R), np.float32)
        rm_nz = np.zeros((C, 2), np.float32)
        vic_row = np.full((C, K), -1, np.int32)
        vic_req = np.zeros((C, K, R), np.float32)
        vic_nz = np.zeros((C, K, 2), np.float32)
        for c, (row, victims, _nv) in enumerate(entries):
            cand_rows[c] = row
            for k, pi in enumerate(victims):
                prow = pod_rows.get(pi.pod.uid, -1)
                if prow >= 0:
                    rm_valid[c, prow] = False
                vic_row[c, k] = prow
                r = pi.resource
                vr = np.zeros((R,), np.float32)
                vr[0] = r.milli_cpu
                vr[1] = r.memory / MIB
                vr[2] = r.ephemeral_storage / MIB
                vr[CH_PODS] = 1
                for name, amt in r.scalar_resources.items():
                    ch = table.rname.get(name)
                    if ch >= 0:
                        vr[4 + ch] = amt
                vic_req[c, k] = vr
                vic_nz[c, k, 0] = pi.non_zero_cpu
                vic_nz[c, k, 1] = pi.non_zero_mem / MIB
                rm_req[c] += vr
                rm_nz[c] += vic_nz[c, k]
        # pad rows: candidate 0's row with no removals (fits0 false unless
        # genuinely feasible; padded candidates are dropped below)
        for c in range(len(entries), C):
            cand_rows[c] = entries[0][0]

        if self._batch1 is None:
            self._batch1 = self._pod_batch1(pod, cycle)
        fits0, reprieved = _whatif_reprieve(
            self._cluster_with_nominated(pod, cycle), self._batch1, cfg_w,
            jnp.asarray(cand_rows), jnp.asarray(rm_valid),
            jnp.asarray(rm_req), jnp.asarray(rm_nz), jnp.asarray(vic_row),
            jnp.asarray(vic_req), jnp.asarray(vic_nz))
        fits0 = np.asarray(fits0)
        reprieved = np.asarray(reprieved)  # [K, C]

        out: Dict[str, Victims] = {}
        for c, (row, victims, n_violating) in enumerate(entries):
            if not fits0[c]:
                continue
            final = [victims[k].pod for k in range(len(victims))
                     if not reprieved[k, c]]
            num_viol = sum(1 for k in range(min(n_violating, len(victims)))
                           if not reprieved[k, c])
            ni = cycle.node_infos[row]
            if not self._host_filters_pass(fwk, pod, ni,
                                           {p.uid for p in final}):
                continue
            out[ni.node_name] = Victims(pods=final,
                                        num_pdb_violations=num_viol)
        return out

    def _pod_rows(self, cycle: CycleContext) -> Dict[str, int]:
        """pod uid -> existing-pod tensor row.  Chained clusters carry the
        mapping explicitly (rows diverge from build order); otherwise it is
        the build order of state/tensors.py SnapshotBuilder.build."""
        if cycle.pod_rows is not None:
            return cycle.pod_rows
        rows: Dict[str, int] = {}
        row = 0
        for ni in cycle.node_infos:
            for pi in ni.pods:
                rows[pi.pod.uid] = row
                row += 1
        return rows

    def _host_filters_pass(self, fwk, pod: api.Pod, ni: NodeInfo,
                           removed_uids: set) -> bool:
        if not fwk.has_relevant_host_filters(pod):
            return True
        sim_ni = ni.clone()
        for pi in list(sim_ni.pods):
            if pi.pod.uid in removed_uids:
                sim_ni.remove_pod(pi.pod)
        st = fwk.run_filter_plugins(CycleState(), pod, sim_ni)
        return st.is_success()

    # ------------------------------------------------------------- extenders

    def _process_with_extenders(self, pod: api.Pod,
                                node_victims: Dict[str, Victims]
                                ) -> Dict[str, Victims]:
        """reference: generic_scheduler.go:317 processPreemptionWithExtenders
        + core/extender.go:317 ProcessPreemption."""
        if not node_victims:
            return node_victims
        for ext in self.sched.extenders:
            if not (ext.supports_preemption() and ext.is_interested(pod)):
                continue
            try:
                node_victims = ext.process_preemption(pod, node_victims)
            except Exception:
                if getattr(ext, "ignorable", False):
                    continue
                return {}
            if not node_victims:
                return {}
        return node_victims


# ---------------------------------------------------------------------------
# pure functions (host)


def filter_pods_with_pdb_violation(pods: List[api.Pod],
                                   pdbs) -> Tuple[List[api.Pod], List[api.Pod]]:
    """reference: generic_scheduler.go:1118 filterPodsWithPDBViolation."""
    violating, non_violating = [], []
    remaining = {id(pdb): pdb.disruptions_allowed for pdb in pdbs}
    for p in pods:
        hit = False
        for pdb in pdbs:
            if pdb.metadata.namespace != p.namespace:
                continue
            if pdb.selector is not None and pdb.selector.matches(
                    p.metadata.labels):
                if remaining[id(pdb)] <= 0:
                    hit = True
                else:
                    remaining[id(pdb)] -= 1
        (violating if hit else non_violating).append(p)
    return violating, non_violating


def pick_one_node_for_preemption(node_victims: Dict[str, Victims]) -> Optional[str]:
    """reference: generic_scheduler.go:729 — lexicographic tie-break:
    1. fewest PDB violations
    2. lowest highest-victim-priority
    3. lowest sum of victim priorities
    4. fewest victims
    5. latest earliest start time of highest-priority victim
    6. first in iteration order (reference returns the first remaining)."""
    if not node_victims:
        return None
    nodes = list(node_victims)

    def metric(fns):
        nonlocal nodes
        vals = {n: fns(node_victims[n]) for n in nodes}
        best = min(vals.values())
        nodes = [n for n in nodes if vals[n] == best]

    metric(lambda v: v.num_pdb_violations)
    if len(nodes) == 1:
        return nodes[0]
    metric(lambda v: max((p.priority() for p in v.pods), default=-2**31))
    if len(nodes) == 1:
        return nodes[0]
    metric(lambda v: sum(p.priority() for p in v.pods))
    if len(nodes) == 1:
        return nodes[0]
    metric(lambda v: len(v.pods))
    if len(nodes) == 1:
        return nodes[0]
    # latest start time of the highest-priority victim (max => min of -ts)
    def neg_latest_start(v: Victims):
        if not v.pods:
            return 0.0
        top = max(v.pods, key=lambda p: p.priority())
        return -top.metadata.creation_timestamp
    metric(neg_latest_start)
    return nodes[0]
