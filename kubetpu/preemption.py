"""Preemption: batched what-if victim selection.

reference: pkg/scheduler/core/generic_scheduler.go — Preempt :252,
podEligibleToPreemptOthers :1063, nodesWherePreemptionMightHelp :1041,
selectNodesForPreemption :858, selectVictimsOnNode :949 (clone + remove
lower-priority pods + re-run filters + reprieve by PDB then priority),
pickOneNodeForPreemption :729 (6-criteria lexicographic tie-break),
getLowerPriorityNominatedPods :360; invoked from scheduler.go:391 preempt.

TPU shape of the what-if: the reference clones one NodeInfo per candidate
and re-runs all filter plugins against it.  Here the clone is a *mask
flip*: victims are existing-pod rows in the already-built cluster tensors,
so "remove the victims of node n" = clear their pod_valid bits and subtract
their resource rows — then ONE jitted filter pass answers "does the pod now
fit on n".  The candidate scan batches those passes; the data-dependent
reprieve loop (:1004-1037) stays host-side, exactly as SURVEY.md §7 planned.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .api import types as api
from .framework.interface import CycleState
from .framework.types import NodeInfo, PodInfo
from .models import programs
from .models.batch import PodBatchBuilder
from .state.tensors import MIB, CH_PODS, SnapshotBuilder


class Victims:
    __slots__ = ("pods", "num_pdb_violations")

    def __init__(self, pods: List[api.Pod], num_pdb_violations: int):
        self.pods = pods
        self.num_pdb_violations = num_pdb_violations


class Preemptor:
    def __init__(self, scheduler, max_detailed_candidates: int = 16):
        self.sched = scheduler
        self.max_detailed_candidates = max_detailed_candidates

    # ------------------------------------------------------------------ entry

    def preempt(self, fwk, state: CycleState, pod: api.Pod) -> Optional[str]:
        """reference: scheduler.go:391 + generic_scheduler.go:252 Preempt.
        Returns the nominated node name, or None."""
        sched = self.sched
        pod = sched.store.get_pod(pod.namespace, pod.metadata.name) or pod
        if not self._eligible(pod):
            return None
        sched.cache.update_snapshot(sched.snapshot)
        node_infos = sched.snapshot.node_info_list
        if not node_infos:
            return None

        cand = self._nodes_where_preemption_might_help(fwk, pod, node_infos)
        if not cand:
            return None
        pdbs = sched.store.list("PodDisruptionBudget")
        node_victims = self._select_nodes_for_preemption(fwk, pod, cand, pdbs)
        if not node_victims:
            return None
        best = pick_one_node_for_preemption(node_victims)
        if best is None:
            return None

        victims = node_victims[best]
        for victim in victims.pods:
            # delete victims via the API (reference: scheduler.go:403-415)
            try:
                sched.store.delete(victim)
            except Exception:
                pass
            if sched.recorder:
                sched.recorder.event(victim, "Normal", "Preempted",
                                     f"by {pod.namespace}/{pod.metadata.name} "
                                     f"on node {best}")
        # reject lower-priority waiting (Permit) pods on the node
        def maybe_reject(wp):
            if (wp.pod.priority() < pod.priority()):
                wp.reject("preempted")
        fwk.iterate_over_waiting_pods(maybe_reject)
        # clear nomination of lower-priority pods nominated to this node
        for np_ in sched.queue.nominated_pods_for_node(best):
            if np_.priority() < pod.priority():
                sched.queue.delete_nominated_pod_if_exists(np_)
        sched.queue.add_nominated_pod(pod, best)
        return best

    def _eligible(self, pod: api.Pod) -> bool:
        """reference: generic_scheduler.go:1063 podEligibleToPreemptOthers —
        if the pod already nominated a node and a lower-priority pod there
        is terminating, wait instead of preempting again."""
        nominated = pod.status.nominated_node_name
        if not nominated:
            return True
        ni = self.sched.snapshot.get(nominated)
        if ni is None:
            return True
        for pi in ni.pods:
            if (pi.pod.metadata.deletion_timestamp is not None
                    and pi.pod.priority() < pod.priority()):
                return False
        return True

    # ------------------------------------------------------- candidate nodes

    def _nodes_where_preemption_might_help(self, fwk, pod: api.Pod,
                                           node_infos: Sequence[NodeInfo]):
        """reference: generic_scheduler.go:1041 — skip nodes whose failure
        was UnschedulableAndUnresolvable.  One device pass recovers the
        per-node unresolvable verdicts."""
        import jax
        builder = SnapshotBuilder(
            hard_pod_affinity_weight=fwk.hard_pod_affinity_weight)
        pinfos = [PodInfo(pod)]
        builder.intern_pending(pinfos)
        host = builder.build(list(node_infos))
        cluster = host.to_device()
        pb = PodBatchBuilder(builder.table)
        batch = jax.tree.map(np.asarray, pb.build(
            pinfos,
            spread_selectors=[self.sched.store.default_spread_selector(pod)]))
        cfg = programs.ProgramConfig(
            filters=fwk.tensor_filters, scores=fwk.tensor_scores,
            hostname_topokey=max(
                builder.table.topokey.get(api.LABEL_HOSTNAME), 0),
            plugin_args=fwk.tensor_plugin_args(builder.table))
        res = programs.filter_and_score(cluster, batch, cfg)
        feasible = np.asarray(res.feasible)[0, :len(node_infos)]
        unresolvable = np.asarray(res.unresolvable)[0, :len(node_infos)]
        self._sim = (builder, host, pinfos, batch, cfg)  # reused by the sim
        return [ni for ni, f, u in zip(node_infos, feasible, unresolvable)
                if not f and not u]

    # -------------------------------------------------------- victim search

    def _select_nodes_for_preemption(self, fwk, pod: api.Pod,
                                     candidates: Sequence[NodeInfo],
                                     pdbs) -> Dict[str, Victims]:
        """reference: generic_scheduler.go:858 (parallel what-if).  Ranks
        candidates by cheap host-side stats, then runs the detailed
        (device-checked) simulation on the strongest few."""
        prio = pod.priority()
        with_victims = []
        for ni in candidates:
            lower = [pi.pod for pi in ni.pods if pi.pod.priority() < prio]
            if not lower:
                continue
            with_victims.append((ni, lower))
        # cheap pre-rank approximating pickOneNode's criteria so the
        # detailed cap keeps the likely winners
        def rank(item):
            ni, lower = item
            return (max(p.priority() for p in lower),
                    sum(p.priority() for p in lower), len(lower))
        with_victims.sort(key=rank)
        out: Dict[str, Victims] = {}
        for ni, lower in with_victims[: self.max_detailed_candidates]:
            v = self._select_victims_on_node(fwk, pod, ni, lower, pdbs)
            if v is not None:
                out[ni.node_name] = v
        return out

    def _select_victims_on_node(self, fwk, pod: api.Pod, ni: NodeInfo,
                                lower: List[api.Pod], pdbs) -> Optional[Victims]:
        """reference: generic_scheduler.go:949 selectVictimsOnNode."""
        node_row = self._node_row(ni)
        removed = set(p.uid for p in lower)
        if not self._fits(fwk, pod, ni, node_row, removed):
            return None
        violating, non_violating = filter_pods_with_pdb_violation(lower, pdbs)

        victims: List[api.Pod] = []
        num_violating = 0

        def reprieve(p: api.Pod) -> bool:
            # try adding p back; keep it if the pod still fits
            removed.discard(p.uid)
            if self._fits(fwk, pod, ni, node_row, removed):
                return True
            removed.add(p.uid)
            victims.append(p)
            return False

        # reprieve in priority order, PDB-violating pods first
        # (reference: :1004-1037)
        for p in sorted(violating, key=lambda x: -x.priority()):
            if not reprieve(p):
                num_violating += 1
        for p in sorted(non_violating, key=lambda x: -x.priority()):
            reprieve(p)
        return Victims(pods=victims, num_pdb_violations=num_violating)

    # ------------------------------------------------------- device what-if

    def _node_row(self, ni: NodeInfo) -> int:
        for i, other in enumerate(self.sched.snapshot.node_info_list):
            if other.node_name == ni.node_name:
                return i
        raise KeyError(ni.node_name)

    def _fits(self, fwk, pod: api.Pod, ni: NodeInfo, node_row: int,
              removed_uids: set) -> bool:
        """Does `pod` pass all tensor filters on node `node_row` with the
        given pods removed?  One B=1 jitted pass over mask-flipped tensors
        (the clone-free NodeInfo.Clone of generic_scheduler.go:871)."""
        import jax
        builder, host, pinfos, batch, cfg = self._sim
        d = dict(host.arrays)
        pod_valid = d["pod_valid"].copy()
        req = d["requested"].copy()
        nz = d["nonzero_requested"].copy()
        # find victim rows: existing pods of this node with removed uids
        row = 0
        for n_idx, ninfo in enumerate(self.sched.snapshot.node_info_list):
            for pi in ninfo.pods:
                if n_idx == node_row and pi.pod.uid in removed_uids:
                    pod_valid[row] = False
                    r = pi.resource
                    req[node_row, 0] -= r.milli_cpu
                    req[node_row, 1] -= r.memory / MIB
                    req[node_row, 2] -= r.ephemeral_storage / MIB
                    req[node_row, CH_PODS] -= 1
                    nz[node_row, 0] -= pi.non_zero_cpu
                    nz[node_row, 1] -= pi.non_zero_mem / MIB
                row += 1
        d["pod_valid"] = pod_valid
        d["requested"] = req
        d["nonzero_requested"] = nz
        from .state.tensors import HostClusterArrays
        cluster = HostClusterArrays(arrays=d).to_device()
        # host filters must also pass on the victim-adjusted node
        if fwk.has_relevant_host_filters(pod):
            sim_ni = ni.clone()
            for pi in list(sim_ni.pods):
                if pi.pod.uid in removed_uids:
                    sim_ni.remove_pod(pi.pod)
            st = fwk.run_filter_plugins(CycleState(), pod, sim_ni)
            if not st.is_success():
                return False
        res = programs.filter_and_score(cluster, batch, cfg)
        return bool(np.asarray(res.feasible)[0, node_row])


# ---------------------------------------------------------------------------
# pure functions (host)


def filter_pods_with_pdb_violation(pods: List[api.Pod],
                                   pdbs) -> Tuple[List[api.Pod], List[api.Pod]]:
    """reference: generic_scheduler.go:1118 filterPodsWithPDBViolation."""
    violating, non_violating = [], []
    remaining = {id(pdb): pdb.disruptions_allowed for pdb in pdbs}
    for p in pods:
        hit = False
        for pdb in pdbs:
            if pdb.metadata.namespace != p.namespace:
                continue
            if pdb.selector is not None and pdb.selector.matches(
                    p.metadata.labels):
                if remaining[id(pdb)] <= 0:
                    hit = True
                else:
                    remaining[id(pdb)] -= 1
        (violating if hit else non_violating).append(p)
    return violating, non_violating


def pick_one_node_for_preemption(node_victims: Dict[str, Victims]) -> Optional[str]:
    """reference: generic_scheduler.go:729 — lexicographic tie-break:
    1. fewest PDB violations
    2. lowest highest-victim-priority
    3. lowest sum of victim priorities
    4. fewest victims
    5. latest earliest start time of highest-priority victim
    6. first in iteration order (reference returns the first remaining)."""
    if not node_victims:
        return None
    nodes = list(node_victims)

    def metric(fns):
        nonlocal nodes
        vals = {n: fns(node_victims[n]) for n in nodes}
        best = min(vals.values())
        nodes = [n for n in nodes if vals[n] == best]

    metric(lambda v: v.num_pdb_violations)
    if len(nodes) == 1:
        return nodes[0]
    metric(lambda v: max((p.priority() for p in v.pods), default=-2**31))
    if len(nodes) == 1:
        return nodes[0]
    metric(lambda v: sum(p.priority() for p in v.pods))
    if len(nodes) == 1:
        return nodes[0]
    metric(lambda v: len(v.pods))
    if len(nodes) == 1:
        return nodes[0]
    # latest start time of the highest-priority victim (max => min of -ts)
    def neg_latest_start(v: Victims):
        if not v.pods:
            return 0.0
        top = max(v.pods, key=lambda p: p.priority())
        return -top.metadata.creation_timestamp
    metric(neg_latest_start)
    return nodes[0]
