"""Scheduler component configuration objects.

reference: pkg/scheduler/apis/config/types.go — KubeSchedulerConfiguration
:55, KubeSchedulerProfile :115, Plugins :176, PluginSet :217, Plugin :230,
DefaultPercentageOfNodesToScore :251.  YAML decoding/defaulting lives in
kubetpu/apis/load.py; these are the internal (typed) forms.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE = 0  # 0 => adaptive (types.go:251)
DEFAULT_SCHEDULER_NAME = "default-scheduler"

EXTENSION_POINTS = (
    "queue_sort", "pre_filter", "filter", "post_filter", "pre_score",
    "score", "reserve", "permit", "pre_bind", "bind", "post_bind",
    "unreserve",
)


@dataclass
class Plugin:
    """reference: types.go:230 (Plugin — name + weight)."""
    name: str
    weight: int = 0


@dataclass
class PluginSet:
    """reference: types.go:217."""
    enabled: List[Plugin] = field(default_factory=list)
    disabled: List[Plugin] = field(default_factory=list)


@dataclass
class Plugins:
    """One PluginSet per extension point (reference: types.go:176)."""
    queue_sort: PluginSet = field(default_factory=PluginSet)
    pre_filter: PluginSet = field(default_factory=PluginSet)
    filter: PluginSet = field(default_factory=PluginSet)
    post_filter: PluginSet = field(default_factory=PluginSet)
    pre_score: PluginSet = field(default_factory=PluginSet)
    score: PluginSet = field(default_factory=PluginSet)
    reserve: PluginSet = field(default_factory=PluginSet)
    permit: PluginSet = field(default_factory=PluginSet)
    pre_bind: PluginSet = field(default_factory=PluginSet)
    bind: PluginSet = field(default_factory=PluginSet)
    post_bind: PluginSet = field(default_factory=PluginSet)
    unreserve: PluginSet = field(default_factory=PluginSet)

    def apply(self, custom: Optional["Plugins"]) -> "Plugins":
        """Merge a profile's custom plugins over these defaults
        (reference: types.go:195 Plugins.Apply / mergePluginSets)."""
        if custom is None:
            return self
        out = Plugins()
        for ep in EXTENSION_POINTS:
            default: PluginSet = getattr(self, ep)
            override: PluginSet = getattr(custom, ep)
            disabled = {p.name for p in override.disabled}
            star = "*" in disabled
            enabled = [p for p in default.enabled
                       if not star and p.name not in disabled]
            enabled += list(override.enabled)
            setattr(out, ep, PluginSet(enabled=enabled))
        return out


@dataclass
class KubeSchedulerProfile:
    """reference: types.go:115."""
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    plugins: Optional[Plugins] = None
    plugin_config: Dict[str, Any] = field(default_factory=dict)


@dataclass
class KubeSchedulerConfiguration:
    """reference: types.go:55."""
    profiles: List[KubeSchedulerProfile] = field(default_factory=list)
    # scheduling behavior
    percentage_of_nodes_to_score: int = DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE
    pod_initial_backoff_seconds: float = 1.0     # types.go:97
    pod_max_backoff_seconds: float = 10.0        # types.go:103
    # HA / serving
    leader_election: bool = False
    metrics_bind_address: str = ""
    health_bind_address: str = ""
    enable_profiling: bool = True                # types.go:76
    enable_contention_profiling: bool = True
    disable_preemption: bool = False             # types.go:85
    # extenders (reference: types.go:72 Extenders)
    extenders: List[Any] = field(default_factory=list)
    # TPU extensions
    batch_size: int = 256        # device batch (B axis); 1 = exact replay
    # "sequential": the lax.scan replay preserving the reference's serial
    # scheduleOne semantics exactly (scheduler.go:509).  "gang": the
    # conflict-free auction (models/gang.py) — O(rounds) parallel passes,
    # exact capacity/hostPort semantics, topology scored against the
    # snapshot rather than intra-batch placements.
    mode: str = "sequential"
    # Device kernel backend for the gang auction's round loop:
    # "lax"    — the reference path: XLA-fused but stage-separate filter /
    #            score / propose programs (also the bit-match oracle).
    # "pallas" — the fused filter→score→propose megakernel
    #            (kubetpu/ops/pallas_kernels.py): per auction round the
    #            [B, N_tile] mask/score blocks stay in VMEM and only
    #            [B]-sized proposals return to HBM.  Engages only for the
    #            supported surface (term-free batches, default score
    #            family — utils/pallas_backend.unsupported_reason);
    #            anything else falls back to lax with a recorded reason,
    #            and placements are bit-identical either way.
    kernel_backend: str = "lax"
    # Deadline-guarded dispatch (the self-healing runtime): a cycle whose
    # device dispatch errors — or whose dispatch-to-readback wall time
    # exceeds this deadline — is DISCARDED before anything commits: the
    # backend is demoted one rung (pallas -> lax, AOT artifacts ->
    # trace) with a recorded reason, the device residents are
    # invalidated (next cycle resyncs from the host mirror), and the
    # cycle's pods are requeued through the backoff queue — never lost,
    # never double-bound.  0 (default) disables the deadline; dispatch
    # ERRORS are always recovered.  Env override: KUBETPU_DISPATCH_DEADLINE.
    dispatch_deadline_seconds: float = 0.0
    # Transient bind failures (DefaultBinder's transport-exception path)
    # retry this many times before the pod is marked failed, sleeping the
    # pod backoff ladder between attempts (pod_initial_backoff_seconds
    # doubling, capped at pod_max_backoff_seconds) — a once-flaky API
    # server must not cost a placement the cycle already won.  Each retry
    # first checks whether the bind landed server-side (bind is not
    # idempotent; a lost response must not re-POST into a Conflict).
    # Retries run on whichever thread ran bind: the binder pool under
    # async binding (the default), the serving loop under sync binding —
    # where each failing pod can stall it for the summed backoff.
    bind_retries: int = 2
    mesh_shape: Optional[tuple] = None
    # Cycle chaining (gang mode): reuse the auction's materialized cluster
    # as the next cycle's snapshot tensors instead of re-tensorizing
    # (SURVEY §7 delta updates).  Default ON as of round 4: a randomized
    # chain-vs-fresh-rebuild equivalence test under event churn
    # (tests/test_chain.py) proves placements identical, and the measured
    # multi-cycle drain (bench.py chain_drain) shows ~7% e2e at 4096x1000
    # — growing with cluster size, since the saved SnapshotBuilder.build
    # scales with nodes+pods while the chain update is O(batch).  Any
    # store event the chain cannot account for still forces a full
    # rebuild (event-sequence invalidation, scheduler.py).
    chain_cycles: bool = True
    # compile the serving program for the current cluster shape at startup
    # (Scheduler.run), before the first pod arrives — with the persistent
    # XLA cache this is a cache load; cold, it moves the first-cycle
    # compile out of the serving path (VERDICT r3 #7)
    prewarm: bool = True
    # prewarm_ladder > 0 additionally AOT-compiles the pod-axis pow2
    # bucket ladder a growing chained drain will traverse, by dry-running
    # that many chained cycles in a BACKGROUND thread after startup (gang
    # mode; see Scheduler._prewarm_ladder).  Without it, each new bucket
    # a drain grows into stalls serving for its compile.  Measured warm
    # restart (bench.py warm_restart_case, 1024-pod wave x 1000 nodes):
    # first cycle 0.36 s.
    prewarm_ladder: int = 2
    # Pipelined drain (gang + chain_cycles only): schedule_pending
    # dispatches cycle k against the previous cycle's speculative on-device
    # chained cluster BEFORE committing older cycles, so cycle k's device
    # execution overlaps both the commit loop of k-1 and the tensorize of
    # k+1 (SURVEY §7 "batched, donated, overlapped"; the reference's
    # analog is the bind goroutine, scheduler.go:628).  Outcomes therefore
    # LAG up to pipeline_depth-1 cycles: each schedule_pending call
    # returns previously dispatched cycles' outcomes, and final calls
    # with an empty queue flush the in-flight ring one cycle per call.
    # A commit failure or an unaccounted store event discards the
    # speculative dispatches and re-runs those cycles against a rebuilt
    # snapshot; batches needing host filter masks (volume pods)
    # serialize on the in-flight commits, so placements match the
    # synchronous drain.  Known bounded lag: the nominated-pods overlay
    # sees preemption nominations from an in-flight cycle only once it
    # commits (nominations only shrink retry feasibility, never
    # correctness of committed placements).
    pipeline_cycles: bool = False
    # Depth of the pipelined executor's in-flight ring (kubetpu/
    # pipeline.py): the maximum number of cycles in flight at once —
    # prepare(k+1) overlaps device(k) and commit/bind(k-1).  1 = fully
    # synchronous (every cycle commits before the next pops), 2 = the
    # historical double-buffered chain (the default), higher depths park
    # more dispatched-but-uncommitted cycles between schedule_pending
    # calls.  Placements are bit-identical at every depth (the bench
    # pipeline_depth case's gated contract).  Env override:
    # KUBETPU_PIPELINE_DEPTH (an operator can re-depth a live fleet).
    pipeline_depth: int = 2

    def profile_for(self, name: str) -> Optional[KubeSchedulerProfile]:
        for p in self.profiles:
            if p.scheduler_name == name:
                return p
        return None
