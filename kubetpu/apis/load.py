"""Config decoding: versioned KubeSchedulerConfiguration YAML + legacy Policy.

reference: cmd/kube-scheduler/app/options/configfile.go (loadConfigFromFile),
pkg/scheduler/apis/config/v1beta1/defaults.go (defaulting),
pkg/scheduler/apis/config/validation/validation.go,
pkg/scheduler/apis/config/legacy_types.go + framework/plugins/
legacy_registry.go (v1 Policy -> plugin translation, :493/:549).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import yaml

from .config import (DEFAULT_SCHEDULER_NAME, EXTENSION_POINTS,
                     KubeSchedulerConfiguration, KubeSchedulerProfile, Plugin,
                     PluginSet, Plugins)

API_GROUP = "kubescheduler.config.k8s.io"
SUPPORTED_VERSIONS = (f"{API_GROUP}/v1beta1", f"{API_GROUP}/v1alpha2")

_EP_YAML_NAMES = {
    "queueSort": "queue_sort", "preFilter": "pre_filter", "filter": "filter",
    "preScore": "pre_score", "score": "score", "reserve": "reserve",
    "permit": "permit", "preBind": "pre_bind", "bind": "bind",
    "postBind": "post_bind", "unreserve": "unreserve",
}


class ConfigError(ValueError):
    pass


def load_config_file(path: str) -> KubeSchedulerConfiguration:
    """reference: app/options/configfile.go:40 loadConfigFromFile."""
    with open(path) as f:
        doc = yaml.safe_load(f)
    return load_config(doc)


def load_config(doc: Dict[str, Any]) -> KubeSchedulerConfiguration:
    if not isinstance(doc, dict):
        raise ConfigError("config must be a mapping")
    api_version = doc.get("apiVersion", "")
    kind = doc.get("kind", "")
    if kind and kind != "KubeSchedulerConfiguration":
        raise ConfigError(f"unexpected kind {kind!r}")
    if api_version and api_version not in SUPPORTED_VERSIONS:
        raise ConfigError(f"unsupported apiVersion {api_version!r}; "
                          f"supported: {SUPPORTED_VERSIONS}")
    cfg = KubeSchedulerConfiguration()
    cfg.percentage_of_nodes_to_score = doc.get("percentageOfNodesToScore", 0)
    cfg.pod_initial_backoff_seconds = doc.get("podInitialBackoffSeconds", 1.0)
    cfg.pod_max_backoff_seconds = doc.get("podMaxBackoffSeconds", 10.0)
    cfg.disable_preemption = doc.get("disablePreemption", False)
    le = doc.get("leaderElection", {}) or {}
    cfg.leader_election = bool(le.get("leaderElect", False))
    cfg.metrics_bind_address = doc.get("metricsBindAddress", "")
    cfg.health_bind_address = doc.get("healthzBindAddress", "")
    cfg.extenders = list(doc.get("extenders", []) or [])
    cfg.batch_size = doc.get("batchSize", 256)  # TPU extension
    cfg.mode = doc.get("mode", "sequential")    # TPU extension
    cfg.profiles = [_decode_profile(p) for p in doc.get("profiles", [])]
    apply_defaults(cfg)
    validate(cfg)
    return cfg


def _decode_profile(doc: Dict[str, Any]) -> KubeSchedulerProfile:
    prof = KubeSchedulerProfile(
        scheduler_name=doc.get("schedulerName", DEFAULT_SCHEDULER_NAME))
    plugins_doc = doc.get("plugins")
    if plugins_doc:
        plugins = Plugins()
        for yaml_name, attr in _EP_YAML_NAMES.items():
            ep = plugins_doc.get(yaml_name)
            if not ep:
                continue
            ps = PluginSet(
                enabled=[Plugin(p["name"], p.get("weight", 0))
                         for p in ep.get("enabled", []) or []],
                disabled=[Plugin(p["name"])
                          for p in ep.get("disabled", []) or []])
            setattr(plugins, attr, ps)
        prof.plugins = plugins
    for pc in doc.get("pluginConfig", []) or []:
        prof.plugin_config[pc["name"]] = pc.get("args", {})
    return prof


def apply_defaults(cfg: KubeSchedulerConfiguration) -> None:
    """reference: v1beta1/defaults.go SetDefaults_KubeSchedulerConfiguration."""
    if not cfg.profiles:
        cfg.profiles = [KubeSchedulerProfile()]
    for p in cfg.profiles:
        if not p.scheduler_name:
            p.scheduler_name = DEFAULT_SCHEDULER_NAME
    if cfg.batch_size <= 0:
        cfg.batch_size = 256


def validate(cfg: KubeSchedulerConfiguration) -> None:
    """reference: validation/validation.go ValidateKubeSchedulerConfiguration."""
    errs: List[str] = []
    if not (0 <= cfg.percentage_of_nodes_to_score <= 100):
        errs.append("percentageOfNodesToScore must be in [0, 100]")
    if cfg.pod_initial_backoff_seconds <= 0:
        errs.append("podInitialBackoffSeconds must be > 0")
    if cfg.mode not in ("sequential", "gang"):
        errs.append("mode must be 'sequential' or 'gang'")
    if cfg.pod_max_backoff_seconds < cfg.pod_initial_backoff_seconds:
        errs.append("podMaxBackoffSeconds must be >= podInitialBackoffSeconds")
    names = [p.scheduler_name for p in cfg.profiles]
    if len(set(names)) != len(names):
        errs.append("duplicate scheduler name in profiles")
    for p in cfg.profiles:
        if p.plugins is None:
            continue
        for ep in EXTENSION_POINTS:
            ps: PluginSet = getattr(p.plugins, ep)
            for pl in ps.enabled:
                if ep == "score" and pl.weight < 0:
                    errs.append(f"plugin {pl.name}: negative weight")
    if errs:
        raise ConfigError("; ".join(errs))


# ---------------------------------------------------------------------------
# legacy v1 Policy (reference: legacy_types.go + legacy_registry.go)

# predicate name -> filter plugins (reference: legacy_registry.go:146-241)
_PREDICATE_TO_PLUGINS: Dict[str, List[str]] = {
    "PodFitsResources": ["NodeResourcesFit"],
    "PodFitsHostPorts": ["NodePorts"],
    "HostName": ["NodeName"],
    "MatchNodeSelector": ["NodeAffinity"],
    "NoDiskConflict": ["VolumeRestrictions"],
    "PodToleratesNodeTaints": ["TaintToleration"],
    "CheckNodeUnschedulable": ["NodeUnschedulable"],
    "CheckVolumeBinding": ["VolumeBinding"],
    "NoVolumeZoneConflict": ["VolumeZone"],
    "MaxCSIVolumeCountPred": ["NodeVolumeLimits"],
    "MaxEBSVolumeCount": ["NodeVolumeLimits"],
    "MaxGCEPDVolumeCount": ["NodeVolumeLimits"],
    "MaxAzureDiskVolumeCount": ["NodeVolumeLimits"],
    "MatchInterPodAffinity": ["InterPodAffinity"],
    "EvenPodsSpreadPred": ["PodTopologySpread"],
    "GeneralPredicates": ["NodeResourcesFit", "NodeName", "NodePorts",
                          "NodeAffinity"],
}

# priority name -> (score plugin, also_pre_score)
_PRIORITY_TO_PLUGIN: Dict[str, str] = {
    "LeastRequestedPriority": "NodeResourcesLeastAllocated",
    "MostRequestedPriority": "NodeResourcesMostAllocated",
    "BalancedResourceAllocation": "NodeResourcesBalancedAllocation",
    "SelectorSpreadPriority": "DefaultPodTopologySpread",
    "InterPodAffinityPriority": "InterPodAffinity",
    "NodeAffinityPriority": "NodeAffinity",
    "TaintTolerationPriority": "TaintToleration",
    "ImageLocalityPriority": "ImageLocality",
    "NodePreferAvoidPodsPriority": "NodePreferAvoidPods",
    "EvenPodsSpreadPriority": "PodTopologySpread",
}

# default predicate/priority sets when the Policy omits them
# (reference: legacy_registry.go ApplyPredicatePolicy defaults)
_DEFAULT_PREDICATES = ["CheckNodeUnschedulable", "GeneralPredicates",
                      "PodToleratesNodeTaints", "NoDiskConflict",
                      "CheckVolumeBinding", "NoVolumeZoneConflict",
                      "MaxCSIVolumeCountPred", "MatchInterPodAffinity",
                      "EvenPodsSpreadPred"]
_DEFAULT_PRIORITIES = {"LeastRequestedPriority": 1,
                       "BalancedResourceAllocation": 1,
                       "NodePreferAvoidPodsPriority": 10000,
                       "NodeAffinityPriority": 1,
                       "TaintTolerationPriority": 1,
                       "InterPodAffinityPriority": 1,
                       "SelectorSpreadPriority": 1,
                       "EvenPodsSpreadPriority": 2}

_FILTER_ORDER = ["NodeUnschedulable", "NodeResourcesFit", "NodeName",
                 "NodePorts", "NodeAffinity", "VolumeRestrictions",
                 "TaintToleration", "NodeVolumeLimits", "VolumeBinding",
                 "VolumeZone", "PodTopologySpread", "InterPodAffinity"]


def load_policy(doc: Dict[str, Any]) -> KubeSchedulerConfiguration:
    """Translate a v1 Policy into a single-profile configuration
    (reference: scheduler.go:266-336 createFromConfig +
    legacy_registry.go ProcessPredicatePolicy/ProcessPriorityPolicy)."""
    if doc.get("kind") not in (None, "Policy"):
        raise ConfigError(f"unexpected kind {doc.get('kind')!r}")
    predicates = doc.get("predicates")
    priorities = doc.get("priorities")

    filter_names: List[str] = []
    if predicates is None:
        pred_names = list(_DEFAULT_PREDICATES)
    else:
        pred_names = [p["name"] for p in predicates]
    for name in pred_names:
        plugins = _PREDICATE_TO_PLUGINS.get(name)
        if plugins is None:
            raise ConfigError(f"unknown predicate {name!r}")
        for pl in plugins:
            if pl not in filter_names:
                filter_names.append(pl)
    filter_names.sort(key=lambda n: _FILTER_ORDER.index(n)
                      if n in _FILTER_ORDER else 99)

    score_weights: Dict[str, int] = {}
    if priorities is None:
        prio_items = list(_DEFAULT_PRIORITIES.items())
    else:
        prio_items = [(p["name"], p.get("weight", 1)) for p in priorities]
    for name, weight in prio_items:
        pl = _PRIORITY_TO_PLUGIN.get(name)
        if pl is None:
            raise ConfigError(f"unknown priority {name!r}")
        score_weights[pl] = score_weights.get(pl, 0) + weight

    star = [Plugin("*")]  # a Policy replaces the defaults wholesale
    plugins = Plugins(
        queue_sort=PluginSet(enabled=[Plugin("PrioritySort")], disabled=list(star)),
        pre_filter=PluginSet(enabled=[
            Plugin(n) for n in filter_names
            if n in ("NodeResourcesFit", "NodePorts", "PodTopologySpread",
                     "InterPodAffinity", "VolumeBinding")], disabled=list(star)),
        filter=PluginSet(enabled=[Plugin(n) for n in filter_names],
                         disabled=list(star)),
        pre_score=PluginSet(disabled=list(star)),
        score=PluginSet(enabled=[Plugin(n, w)
                                 for n, w in score_weights.items()],
                        disabled=list(star)),
        reserve=PluginSet(enabled=[Plugin("VolumeBinding")]
                          if "VolumeBinding" in filter_names else [],
                          disabled=list(star)),
        unreserve=PluginSet(enabled=[Plugin("VolumeBinding")]
                            if "VolumeBinding" in filter_names else [],
                            disabled=list(star)),
        pre_bind=PluginSet(enabled=[Plugin("VolumeBinding")]
                           if "VolumeBinding" in filter_names else [],
                           disabled=list(star)),
        post_bind=PluginSet(disabled=list(star)),
        permit=PluginSet(disabled=list(star)),
        bind=PluginSet(enabled=[Plugin("DefaultBinder")], disabled=list(star)),
    )
    prof = KubeSchedulerProfile(plugins=plugins)
    if "hardPodAffinitySymmetricWeight" in doc:
        prof.plugin_config["InterPodAffinity"] = {
            "hardPodAffinityWeight": doc["hardPodAffinitySymmetricWeight"]}
    cfg = KubeSchedulerConfiguration(profiles=[prof])
    cfg.extenders = list(doc.get("extenders", []) or [])
    validate(cfg)
    return cfg
