"""Config decoding: versioned KubeSchedulerConfiguration YAML + legacy Policy.

reference: cmd/kube-scheduler/app/options/configfile.go (loadConfigFromFile),
pkg/scheduler/apis/config/v1beta1/defaults.go (defaulting),
pkg/scheduler/apis/config/validation/validation.go,
pkg/scheduler/apis/config/legacy_types.go + framework/plugins/
legacy_registry.go (v1 Policy -> plugin translation, :493/:549).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import yaml

from .config import (DEFAULT_SCHEDULER_NAME, EXTENSION_POINTS,
                     KubeSchedulerConfiguration, KubeSchedulerProfile, Plugin,
                     PluginSet, Plugins)

API_GROUP = "kubescheduler.config.k8s.io"
SUPPORTED_VERSIONS = (f"{API_GROUP}/v1beta1", f"{API_GROUP}/v1alpha2")

_EP_YAML_NAMES = {
    "queueSort": "queue_sort", "preFilter": "pre_filter", "filter": "filter",
    "preScore": "pre_score", "score": "score", "reserve": "reserve",
    "permit": "permit", "preBind": "pre_bind", "bind": "bind",
    "postBind": "post_bind", "unreserve": "unreserve",
}


class ConfigError(ValueError):
    pass


def load_config_file(path: str) -> KubeSchedulerConfiguration:
    """reference: app/options/configfile.go:40 loadConfigFromFile."""
    with open(path) as f:
        doc = yaml.safe_load(f)
    return load_config(doc)


def load_config(doc: Dict[str, Any]) -> KubeSchedulerConfiguration:
    if not isinstance(doc, dict):
        raise ConfigError("config must be a mapping")
    api_version = doc.get("apiVersion", "")
    kind = doc.get("kind", "")
    if kind and kind != "KubeSchedulerConfiguration":
        raise ConfigError(f"unexpected kind {kind!r}")
    if api_version and api_version not in SUPPORTED_VERSIONS:
        raise ConfigError(f"unsupported apiVersion {api_version!r}; "
                          f"supported: {SUPPORTED_VERSIONS}")
    cfg = KubeSchedulerConfiguration()
    cfg.percentage_of_nodes_to_score = doc.get("percentageOfNodesToScore", 0)
    cfg.pod_initial_backoff_seconds = doc.get("podInitialBackoffSeconds", 1.0)
    cfg.pod_max_backoff_seconds = doc.get("podMaxBackoffSeconds", 10.0)
    cfg.disable_preemption = doc.get("disablePreemption", False)
    le = doc.get("leaderElection", {}) or {}
    cfg.leader_election = bool(le.get("leaderElect", False))
    cfg.metrics_bind_address = doc.get("metricsBindAddress", "")
    cfg.health_bind_address = doc.get("healthzBindAddress", "")
    cfg.extenders = list(doc.get("extenders", []) or [])
    cfg.batch_size = doc.get("batchSize", 256)  # TPU extension
    cfg.mode = doc.get("mode", "sequential")    # TPU extension
    cfg.kernel_backend = doc.get("kernelBackend", "lax")  # TPU extension
    # TPU extension: depth-k pipelined executor (kubetpu/pipeline.py)
    cfg.pipeline_cycles = bool(doc.get("pipelineCycles", False))
    cfg.pipeline_depth = int(doc.get("pipelineDepth", 2))
    cfg.profiles = [_decode_profile(p) for p in doc.get("profiles", [])]
    apply_defaults(cfg)
    validate(cfg)
    return cfg


def _decode_profile(doc: Dict[str, Any]) -> KubeSchedulerProfile:
    prof = KubeSchedulerProfile(
        scheduler_name=doc.get("schedulerName", DEFAULT_SCHEDULER_NAME))
    plugins_doc = doc.get("plugins")
    if plugins_doc:
        plugins = Plugins()
        for yaml_name, attr in _EP_YAML_NAMES.items():
            ep = plugins_doc.get(yaml_name)
            if not ep:
                continue
            ps = PluginSet(
                enabled=[Plugin(p["name"], p.get("weight", 0))
                         for p in ep.get("enabled", []) or []],
                disabled=[Plugin(p["name"])
                          for p in ep.get("disabled", []) or []])
            setattr(plugins, attr, ps)
        prof.plugins = plugins
    for pc in doc.get("pluginConfig", []) or []:
        prof.plugin_config[pc["name"]] = pc.get("args", {})
    return prof


def apply_defaults(cfg: KubeSchedulerConfiguration) -> None:
    """reference: v1beta1/defaults.go SetDefaults_KubeSchedulerConfiguration."""
    if not cfg.profiles:
        cfg.profiles = [KubeSchedulerProfile()]
    for p in cfg.profiles:
        if not p.scheduler_name:
            p.scheduler_name = DEFAULT_SCHEDULER_NAME
    if cfg.batch_size <= 0:
        cfg.batch_size = 256


def validate(cfg: KubeSchedulerConfiguration,
             registry_names=None) -> None:
    """reference: validation/validation.go
    ValidateKubeSchedulerConfiguration (+ the plugin-existence and
    queue-sort checks the reference performs at framework build time,
    framework.go:205 NewFramework; VERDICT r3 #10).

    registry_names: known plugin names for plugin-EXISTENCE checks.  When
    None (config load time), existence is NOT checked — out-of-tree
    plugins are resolvable only once the merged registry exists, so the
    Scheduler re-validates with its actual registry at construction (the
    reference likewise rejects unknown plugins at framework build time,
    framework.go:205, not at config decode)."""
    errs: List[str] = []
    if not (0 <= cfg.percentage_of_nodes_to_score <= 100):
        errs.append("percentageOfNodesToScore must be in [0, 100]")
    if cfg.pod_initial_backoff_seconds <= 0:
        errs.append("podInitialBackoffSeconds must be > 0")
    if cfg.mode not in ("sequential", "gang"):
        errs.append("mode must be 'sequential' or 'gang'")
    if cfg.kernel_backend not in ("lax", "pallas"):
        errs.append("kernelBackend must be 'lax' or 'pallas'")
    if int(getattr(cfg, "pipeline_depth", 2) or 0) < 1:
        errs.append("pipelineDepth must be >= 1")
    if cfg.pod_max_backoff_seconds < cfg.pod_initial_backoff_seconds:
        errs.append("podMaxBackoffSeconds must be >= podInitialBackoffSeconds")
    names = [p.scheduler_name for p in cfg.profiles]
    if len(set(names)) != len(names):
        errs.append("duplicate scheduler name in profiles")
    known = None if registry_names is None else set(registry_names)
    queue_sorts = set()
    for p in cfg.profiles:
        hw = p.plugin_config.get("InterPodAffinity", {}) \
            .get("hardPodAffinityWeight")
        if hw is not None and not (0 <= int(hw) <= 100):
            errs.append(f"profile {p.scheduler_name}: "
                        "hardPodAffinityWeight must be in [0, 100]")
        if known is not None:
            for name in p.plugin_config:
                if name not in known:
                    errs.append(f"profile {p.scheduler_name}: pluginConfig "
                                f"for unknown plugin {name!r}")
        if p.plugins is None:
            queue_sorts.add(("PrioritySort",))   # the default queue sort
            continue
        for ep in EXTENSION_POINTS:
            ps: PluginSet = getattr(p.plugins, ep)
            seen = set()
            weight_total = 0
            for pl in ps.enabled:
                if known is not None and pl.name != "*" \
                        and pl.name not in known:
                    errs.append(f"profile {p.scheduler_name}: unknown "
                                f"plugin {pl.name!r} at {ep}")
                if pl.name in seen:
                    errs.append(f"profile {p.scheduler_name}: plugin "
                                f"{pl.name!r} enabled twice at {ep}")
                seen.add(pl.name)
                if ep == "score":
                    if pl.weight < 0:
                        errs.append(f"plugin {pl.name}: negative weight")
                    weight_total += max(pl.weight, 0)
            # the reference guards int64 overflow of total weighted score
            # (framework.go:638); our combine is exact-integer f32, so the
            # cap is 2^24 / MaxNodeScore total weight
            if ep == "score" and weight_total * 100 >= 2 ** 24:
                errs.append(f"profile {p.scheduler_name}: total score "
                            "weight too large (score sums would lose "
                            "integer exactness)")
            for pl in ps.disabled:
                if known is not None and pl.name != "*" \
                        and pl.name not in known:
                    errs.append(f"profile {p.scheduler_name}: unknown "
                                f"disabled plugin {pl.name!r} at {ep}")
        queue_sorts.add(tuple(sorted(
            pl.name for pl in p.plugins.queue_sort.enabled))
            or ("PrioritySort",))
    # all profiles must share one queue sort: there is ONE queue
    # (reference: validation.go validateCommonQueueSort)
    if len(queue_sorts) > 1:
        errs.append("all profiles must use the same queueSort plugin set")
    # extenders (reference: validation.go:129 validateExtenders)
    binders = 0
    for i, e in enumerate(cfg.extenders):
        e = e if isinstance(e, dict) else vars(e)
        if e.get("prioritizeVerb") and int(e.get("weight", 0)) <= 0:
            errs.append(f"extender[{i}]: prioritizeVerb requires a "
                        "positive weight")
        if e.get("bindVerb"):
            binders += 1
    if binders > 1:
        errs.append("only one extender can implement bind")
    if errs:
        raise ConfigError("; ".join(errs))


# ---------------------------------------------------------------------------
# legacy v1 Policy (reference: legacy_types.go + legacy_registry.go)

# predicate name -> filter plugins (reference: legacy_registry.go:146-241)
_PREDICATE_TO_PLUGINS: Dict[str, List[str]] = {
    "PodFitsResources": ["NodeResourcesFit"],
    "PodFitsHostPorts": ["NodePorts"],
    "HostName": ["NodeName"],
    "MatchNodeSelector": ["NodeAffinity"],
    "NoDiskConflict": ["VolumeRestrictions"],
    "PodToleratesNodeTaints": ["TaintToleration"],
    "CheckNodeUnschedulable": ["NodeUnschedulable"],
    "CheckVolumeBinding": ["VolumeBinding"],
    "NoVolumeZoneConflict": ["VolumeZone"],
    "MaxCSIVolumeCountPred": ["NodeVolumeLimits"],
    "MaxEBSVolumeCount": ["NodeVolumeLimits"],
    "MaxGCEPDVolumeCount": ["NodeVolumeLimits"],
    "MaxAzureDiskVolumeCount": ["NodeVolumeLimits"],
    "MatchInterPodAffinity": ["InterPodAffinity"],
    "EvenPodsSpreadPred": ["PodTopologySpread"],
    "GeneralPredicates": ["NodeResourcesFit", "NodeName", "NodePorts",
                          "NodeAffinity"],
}

# priority name -> (score plugin, also_pre_score)
_PRIORITY_TO_PLUGIN: Dict[str, str] = {
    "LeastRequestedPriority": "NodeResourcesLeastAllocated",
    "MostRequestedPriority": "NodeResourcesMostAllocated",
    "BalancedResourceAllocation": "NodeResourcesBalancedAllocation",
    "SelectorSpreadPriority": "DefaultPodTopologySpread",
    "InterPodAffinityPriority": "InterPodAffinity",
    "NodeAffinityPriority": "NodeAffinity",
    "TaintTolerationPriority": "TaintToleration",
    "ImageLocalityPriority": "ImageLocality",
    "NodePreferAvoidPodsPriority": "NodePreferAvoidPods",
    "EvenPodsSpreadPriority": "PodTopologySpread",
}

# default predicate/priority sets when the Policy omits them
# (reference: legacy_registry.go ApplyPredicatePolicy defaults)
_DEFAULT_PREDICATES = ["CheckNodeUnschedulable", "GeneralPredicates",
                      "PodToleratesNodeTaints", "NoDiskConflict",
                      "CheckVolumeBinding", "NoVolumeZoneConflict",
                      "MaxCSIVolumeCountPred", "MatchInterPodAffinity",
                      "EvenPodsSpreadPred"]
_DEFAULT_PRIORITIES = {"LeastRequestedPriority": 1,
                       "BalancedResourceAllocation": 1,
                       "NodePreferAvoidPodsPriority": 10000,
                       "NodeAffinityPriority": 1,
                       "TaintTolerationPriority": 1,
                       "InterPodAffinityPriority": 1,
                       "SelectorSpreadPriority": 1,
                       "EvenPodsSpreadPriority": 2}

_FILTER_ORDER = ["NodeUnschedulable", "NodeResourcesFit", "NodeName",
                 "NodePorts", "NodeAffinity", "VolumeRestrictions",
                 "TaintToleration", "NodeVolumeLimits", "VolumeBinding",
                 "VolumeZone", "PodTopologySpread", "InterPodAffinity"]


def load_policy(doc: Dict[str, Any]) -> KubeSchedulerConfiguration:
    """Translate a v1 Policy into a single-profile configuration
    (reference: scheduler.go:266-336 createFromConfig +
    legacy_registry.go ProcessPredicatePolicy/ProcessPriorityPolicy)."""
    if doc.get("kind") not in (None, "Policy"):
        raise ConfigError(f"unexpected kind {doc.get('kind')!r}")
    predicates = doc.get("predicates")
    priorities = doc.get("priorities")

    filter_names: List[str] = []
    if predicates is None:
        pred_names = list(_DEFAULT_PREDICATES)
    else:
        pred_names = [p["name"] for p in predicates]
    for name in pred_names:
        plugins = _PREDICATE_TO_PLUGINS.get(name)
        if plugins is None:
            raise ConfigError(f"unknown predicate {name!r}")
        for pl in plugins:
            if pl not in filter_names:
                filter_names.append(pl)
    filter_names.sort(key=lambda n: _FILTER_ORDER.index(n)
                      if n in _FILTER_ORDER else 99)

    score_weights: Dict[str, int] = {}
    if priorities is None:
        prio_items = list(_DEFAULT_PRIORITIES.items())
    else:
        prio_items = [(p["name"], p.get("weight", 1)) for p in priorities]
    for name, weight in prio_items:
        pl = _PRIORITY_TO_PLUGIN.get(name)
        if pl is None:
            raise ConfigError(f"unknown priority {name!r}")
        score_weights[pl] = score_weights.get(pl, 0) + weight

    star = [Plugin("*")]  # a Policy replaces the defaults wholesale
    plugins = Plugins(
        queue_sort=PluginSet(enabled=[Plugin("PrioritySort")], disabled=list(star)),
        pre_filter=PluginSet(enabled=[
            Plugin(n) for n in filter_names
            if n in ("NodeResourcesFit", "NodePorts", "PodTopologySpread",
                     "InterPodAffinity", "VolumeBinding")], disabled=list(star)),
        filter=PluginSet(enabled=[Plugin(n) for n in filter_names],
                         disabled=list(star)),
        pre_score=PluginSet(disabled=list(star)),
        score=PluginSet(enabled=[Plugin(n, w)
                                 for n, w in score_weights.items()],
                        disabled=list(star)),
        reserve=PluginSet(enabled=[Plugin("VolumeBinding")]
                          if "VolumeBinding" in filter_names else [],
                          disabled=list(star)),
        unreserve=PluginSet(enabled=[Plugin("VolumeBinding")]
                            if "VolumeBinding" in filter_names else [],
                            disabled=list(star)),
        pre_bind=PluginSet(enabled=[Plugin("VolumeBinding")]
                           if "VolumeBinding" in filter_names else [],
                           disabled=list(star)),
        post_bind=PluginSet(disabled=list(star)),
        permit=PluginSet(disabled=list(star)),
        bind=PluginSet(enabled=[Plugin("DefaultBinder")], disabled=list(star)),
    )
    prof = KubeSchedulerProfile(plugins=plugins)
    if "hardPodAffinitySymmetricWeight" in doc:
        prof.plugin_config["InterPodAffinity"] = {
            "hardPodAffinityWeight": doc["hardPodAffinitySymmetricWeight"]}
    cfg = KubeSchedulerConfiguration(profiles=[prof])
    cfg.extenders = list(doc.get("extenders", []) or [])
    validate(cfg)
    return cfg
