"""Device-mesh sharding of the scheduling program.

The reference scales one scheduling cycle with 16 chunked goroutines over the
node list (reference: pkg/scheduler/internal/parallelize/parallelism.go:26-43,
used from core/generic_scheduler.go:485 and framework.go:592).  The
TPU-native equivalent shards the dense tensors over a
`jax.sharding.Mesh` and lets XLA's SPMD partitioner insert the collectives
the goroutine fan-in/atomic-counter code did by hand:

  axis "pods"  — data parallelism over the pending-pod batch axis B (the
                 analog of running many scheduleOne loops at once) and over
                 the existing-pods axis P of the snapshot.
  axis "nodes" — the node axis N of every per-node array (the analog of the
                 16-goroutine chunking; also our "sequence parallelism" —
                 SURVEY.md §5: the reference's long axis IS node count).

Per-plugin NormalizeScore needs per-pod min/max over all nodes
(framework.go:613); under this sharding XLA lowers that to an all-reduce
over the "nodes" axis — the collective that replaces the serial
NormalizeScore loop.  Pair/topology segment-sums over sharded pod or node
axes become scatter-adds + psum.  Host code never writes collectives
explicitly; shardings are the whole parallel API, per the scaling-book
recipe (mesh -> annotate -> let XLA insert collectives).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..models import gang, programs, sequential
from ..state.tensors import ClusterTensors

AXIS_PODS = "pods"
AXIS_NODES = "nodes"


def ambient_mesh(mesh: Mesh):
    """Context manager installing ``mesh`` as the ambient mesh for the
    enclosed dispatches.  ``jax.set_mesh`` only exists on newer jax; on
    runtimes without it the legacy ``Mesh`` object is itself a context
    manager with the same effect for committed-sharding dispatch (the
    inputs carry NamedShardings either way — the ambient mesh only backs
    mesh-less intermediates), so fall back to entering the mesh directly."""
    setter = getattr(jax, "set_mesh", None)
    if setter is not None:
        return setter(mesh)
    return mesh

# ClusterTensors fields whose leading axis is the node axis N.
NODE_AXIS_FIELDS = frozenset({
    "allocatable", "requested", "nonzero_requested", "node_valid",
    "unschedulable", "kv", "keymask", "num", "topo_pair", "taints", "ports",
    "images", "avoid_hot", "zone_hot",
})
# ClusterTensors fields whose leading axis is the existing-pods axis P.
POD_AXIS_FIELDS = frozenset({
    "pod_kv", "pod_key", "pod_ns_hot", "pod_node", "pod_valid",
    "pod_terminating",
})


def make_mesh(shape: Optional[Tuple[int, int]] = None,
              devices=None) -> Mesh:
    """Build a ("pods", "nodes") mesh.  Default shape puts all devices on
    the node axis (the reference's only intra-cycle parallel axis).  When
    the default platform cannot satisfy the requested shape (e.g. one
    tunneled TPU chip) but a virtual CPU mesh can
    (--xla_force_host_platform_device_count), fall back to CPU devices so
    the sharded path stays testable without N real chips."""
    if devices is None:
        devices = jax.devices()
        if shape is not None and shape[0] * shape[1] != len(devices):
            try:
                cpus = jax.devices("cpu")
            except RuntimeError:
                cpus = []
            if shape[0] * shape[1] == len(cpus):
                devices = cpus
    devices = list(devices)
    n = len(devices)
    if shape is None:
        shape = (1, n)
    if shape[0] * shape[1] != n:
        raise ValueError(f"mesh shape {shape} != {n} devices")
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, (AXIS_PODS, AXIS_NODES))


def _put(x, sharding: NamedSharding):
    """device_put that also works on MULTI-PROCESS meshes: for
    non-fully-addressable shardings, build the global array from each
    process's addressable shards (device_put would run a cross-process
    same-value assert that trips on NaN padding — NaN != NaN).

    Arrays already committed to the requested sharding pass through
    untouched — the delta-maintained resident cluster
    (state/delta.py DeltaTensorizer with a mesh) re-enters
    shard_cluster every dispatch, and re-``device_put``-ing the whole
    [N, R] tensors each cycle was exactly the host cost the delta
    pipeline removes."""
    if isinstance(x, jax.Array) and x.sharding == sharding:
        return x
    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(x, sharding)
    arr = np.asarray(x)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def shard_cluster(cluster: ClusterTensors, mesh: Mesh,
                  shard_existing_pods: bool = True) -> ClusterTensors:
    """device_put a host/replicated ClusterTensors onto the mesh."""
    out = {}
    for field in ClusterTensors._fields:
        val = getattr(cluster, field)
        if field in NODE_AXIS_FIELDS:
            out[field] = _put(val, NamedSharding(mesh, P(AXIS_NODES)))
        elif field in POD_AXIS_FIELDS and shard_existing_pods:
            out[field] = _put(val, NamedSharding(mesh, P(AXIS_PODS)))
        else:
            out[field] = jax.tree.map(
                lambda x: _put(x, NamedSharding(mesh, P())), val)
    return ClusterTensors(**out)


def shard_batch(batch, mesh: Mesh):
    """Shard every PodBatch leaf on dim 0 over the "pods" axis.  All batch
    leaves lead with B or a flattened B*T axis, so dim-0 sharding is the
    data-parallel split of the pending-pod batch.  Leaves that are
    already jax Arrays pass through without a host round-trip (the
    double-buffered upload path hands an ALREADY-SHARDED batch back in
    at dispatch — np.asarray here would pull every leaf through the
    tunnel just to re-upload it)."""
    n = mesh.shape[AXIS_PODS]

    def put(x):
        if not isinstance(x, jax.Array):
            x = np.asarray(x)
        if x.ndim >= 1 and x.shape[0] % n == 0:
            return _put(x, NamedSharding(mesh, P(AXIS_PODS)))
        return _put(x, NamedSharding(mesh, P()))
    return jax.tree.map(put, batch)


def replicate(tree, mesh: Mesh):
    return jax.tree.map(
        lambda x: _put(x, NamedSharding(mesh, P())), tree)


def sharded_apply_cluster_delta(cluster, delta, mesh: Mesh,
                                donate: bool = True,
                                partitioner: Optional[str] = None):
    """Apply a ClusterDelta to the SHARDED resident cluster, shard-locally:
    the [D]-indexed update tables are tiny and ride replicated, and each
    shard scatters only its locally-owned rows — no shard ever
    re-materializes (or re-uploads) the full [N, R] / [P, L] tensors.
    The cluster keeps its committed shardings, so the next dispatch's
    shard_cluster is a pass-through.

    Default lowering is the EXPLICIT shard_map scatter
    (parallel/shardmap.py apply_cluster_delta_mesh — required for
    pod-axis sharded residents, where the legacy SPMD partitioner
    mis-lowers cross-shard index selection); ``partitioner="gspmd"``
    keeps the old ambient-mesh lowering for comparison/regression use."""
    if (partitioner or "shard_map") == "gspmd":
        from ..models import programs
        delta = replicate(jax.tree.map(np.asarray, delta), mesh)
        with ambient_mesh(mesh):
            return programs.apply_cluster_delta(cluster, delta,
                                                donate=donate)
    from . import shardmap
    return shardmap.apply_cluster_delta_mesh(cluster, delta, mesh,
                                             donate=donate)


def sharded_schedule_batch(cluster, batch, cfg: programs.ProgramConfig, rng,
                           mesh: Mesh, shard_existing_pods: bool = True):
    """One-shot batch scheduling over the mesh.  Inputs are placed with
    shard_cluster/shard_batch; jit consumes the committed shardings and the
    SPMD partitioner derives every intermediate sharding + collective."""
    cluster = shard_cluster(cluster, mesh, shard_existing_pods)
    batch = shard_batch(batch, mesh)
    rng = _put(rng, NamedSharding(mesh, P()))
    with ambient_mesh(mesh):
        return programs.schedule_batch(cluster, batch, cfg, rng)


def sharded_filter_and_score(cluster, batch, cfg: programs.ProgramConfig,
                             mesh: Mesh, host_ok=None,
                             shard_existing_pods: bool = True):
    """filter_and_score over the mesh (the extender path's device half)."""
    cluster = shard_cluster(cluster, mesh, shard_existing_pods)
    batch = shard_batch(batch, mesh)
    with ambient_mesh(mesh):
        return programs.filter_and_score(cluster, batch, cfg,
                                         host_ok=_shard_host_ok(host_ok,
                                                                mesh))


def _shard_host_ok(host_ok, mesh: Mesh):
    if host_ok is None:
        return None
    host_ok = np.asarray(host_ok)
    ok = (host_ok.shape[0] % mesh.shape[AXIS_PODS] == 0
          and host_ok.shape[1] % mesh.shape[AXIS_NODES] == 0)
    spec = P(AXIS_PODS, AXIS_NODES) if ok else P()
    return _put(host_ok, NamedSharding(mesh, spec))


def sharded_schedule_gang(cluster, batch, cfg: programs.ProgramConfig, rng,
                          mesh: Mesh, shard_existing_pods: bool = True,
                          max_rounds: Optional[int] = None,
                          host_ok=None, intra_batch_topology: bool = True,
                          score_bias=None,
                          partitioner: Optional[str] = None):
    """Gang auction over the mesh.  Default lowering is the EXPLICIT
    shard_map auction (parallel/shardmap.py): the [B, N] filter/score
    work shards over both axes, per-pod winners resolve via node-axis
    collectives + a pods-axis all_gather, and admission runs replicated
    — correct on pod-axis (2, 4)/(4, 2) meshes where the legacy SPMD
    partitioner mis-lowers the loop machinery (PR 6 skip markers).
    ``partitioner="gspmd"`` keeps the old derive-everything lowering,
    exact on node-axis (1, N) meshes only."""
    if (partitioner or "shard_map") == "gspmd":
        cluster = shard_cluster(cluster, mesh, shard_existing_pods)
        batch = shard_batch(batch, mesh)
        rng = _put(rng, NamedSharding(mesh, P()))
        with ambient_mesh(mesh):
            return gang.schedule_gang(
                cluster, batch, cfg, rng,
                host_ok=_shard_host_ok(host_ok, mesh),
                max_rounds=max_rounds,
                intra_batch_topology=intra_batch_topology,
                score_bias=_shard_host_ok(score_bias, mesh))
    from . import shardmap
    return shardmap.schedule_gang_mesh(
        cluster, batch, cfg, rng, mesh,
        shard_existing_pods=shard_existing_pods, max_rounds=max_rounds,
        host_ok=host_ok, intra_batch_topology=intra_batch_topology,
        score_bias=score_bias)


def sharded_schedule_sequential(cluster, batch, cfg: programs.ProgramConfig,
                                rng, mesh: Mesh,
                                shard_existing_pods: bool = True,
                                hard_pod_affinity_weight: float = 1.0,
                                host_ok=None, start_index=0,
                                score_bias=None,
                                partitioner: Optional[str] = None):
    """Sequential-replay scan over the mesh.  Default lowering is the
    explicit shard_map program (parallel/shardmap.py): the scan axis
    (pods, in order) is serial by construction, so the per-device body
    replicates the exact single-device scan — the correctness fix for
    the legacy partitioner's cross-shard index selection on pod-axis
    meshes.  ``partitioner="gspmd"`` keeps the old lowering (exact on
    node-axis (1, N) meshes only)."""
    if (partitioner or "shard_map") == "gspmd":
        cluster = shard_cluster(cluster, mesh, shard_existing_pods)
        batch = shard_batch(batch, mesh)
        rng = _put(rng, NamedSharding(mesh, P()))
        with ambient_mesh(mesh):
            return sequential.schedule_sequential(
                cluster, batch, cfg, rng,
                hard_pod_affinity_weight=hard_pod_affinity_weight,
                host_ok=_shard_host_ok(host_ok, mesh),
                start_index=start_index,
                score_bias=_shard_host_ok(score_bias, mesh))
    from . import shardmap
    return shardmap.schedule_sequential_mesh(
        cluster, batch, cfg, rng, mesh,
        shard_existing_pods=shard_existing_pods,
        hard_pod_affinity_weight=hard_pod_affinity_weight,
        host_ok=host_ok, start_index=start_index, score_bias=score_bias)
