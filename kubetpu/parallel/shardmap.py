"""Explicit shard_map programs for pod-axis mesh scale-out.

The GSPMD path (parallel/mesh.py, ``partitioner="gspmd"``) lets the SPMD
partitioner derive every intermediate sharding.  On this jax the LEGACY
partitioner mis-lowers the auction/scan loop machinery when the POD axis
is split — gang contention winners flip and infeasible pods come back
placed (PR 6's env-gated skip markers document the fault class; the
[B, N] kernel family itself lowers correctly, which is why
``schedule_batch`` passes at (2, 4) ungated).  This module sidesteps the
partitioner for the selection core entirely: the cross-shard program is
written out as an explicit ``shard_map`` with hand-placed collectives, so
there is no partitioning decision left for the legacy lowering to get
wrong.

Two surfaces, chosen statically per dispatch (``gang_surface``):

* ``tiled`` — the scale path, term-free batches (the same supported
  surface as the Pallas megakernel, whose decomposition this reuses —
  ops/pallas_kernels.py build_bundle provides the round-invariant
  [S, B, N] planes).  Each device owns a [B/mp, N/mn] tile of the
  filter/score plane; per auction round it

    1. recomputes feasibility + the weighted score combine on its tile
       (per-pod normalization statistics via ``lax.pmax/pmin/psum`` over
       the "nodes" axis — every reduction is a float max/min or an
       integer-valued-f32 sum, exact in any order below 2**24: the
       Pallas oracle's exactness discipline),
    2. proposes GATHER-FREE: the selectHost categorical decomposes into
       ``argmax(where(tie, gumbel, -2**62))`` (the PR 8 pillar), and the
       cross-shard argmax resolves without any cross-shard gather — a
       strict-improvement (best, gumbel) pmax pair plus a pmin over
       qualifying GLOBAL node indices reproduces jnp.argmax's
       first-index tie-break bit-for-bit,
    3. resolves contention collectively: per-pod winners
       ``lax.all_gather`` over the "pods" axis and every device runs the
       IDENTICAL O(B) segmented-reduce admission
       (models/gang.py admission_mask/admission_sums — the same
       functions the single-device round calls), so no readback, sort or
       carry ever leaves the device.

  The [B, N] plane work — the term that forces the north-star shape off
  one chip — is the part that shards over BOTH mesh axes; the [N, R]
  capacity carries and [B] assignment vector ride replicated (~100 KB at
  10k nodes).

* ``replicated`` — the correctness surface for everything else
  (intra-batch topology, exotic score plugins, non-divisible axes):
  every device traces the SAME single-device program body
  (``gang._gang_program`` / ``sequential._sequential_program``) on
  replicated inputs.  Bit-identity with the single-device golden is by
  construction — it IS the single-device program, and shard_map's manual
  lowering leaves the partitioner nothing to mis-lower.  This replicates
  compute across the mesh (documented; the scale story is the tiled
  auction — topology batches joining it is ROADMAP item 2's intra-batch
  surface work).

The delta scatter gets the same treatment: ``apply_cluster_delta_mesh``
shifts the replicated [D]-row tables into each shard's LOCAL row space
(out-of-shard rows map one-past-capacity, which ``mode="drop"``
discards) and applies the ordinary ``programs._apply_cluster_delta``
per shard — the resident cluster stays pre-sharded across cycles on the
pod axis too, with no cross-shard scatter for the partitioner to lower.

Meshes enter jit static args as a registry KEY (axis layout + device
ids) rather than the Mesh object: the key digests stably into the AOT
signature (utils/aot.py) while the trace-time body looks the Mesh back
up from ``_MESHES``.
"""

from __future__ import annotations

import functools
import threading
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..models import gang, programs, sequential
from ..models.gang import GangResult, admission_mask, admission_sums
from ..ops import kernels as K
from ..ops import pallas_kernels as PK
from ..state.tensors import CH_CPU, CH_MEM, CH_PODS, N_FIXED_CHANNELS

AXIS_PODS = "pods"
AXIS_NODES = "nodes"
_NEG = jnp.float32(-2**62)
MAX_NODE_SCORE = K.MAX_NODE_SCORE

# trace-time Mesh registry: the hashable KEY is the jit/AOT static, the
# Mesh object never enters a signature.  Written by register_mesh (any
# thread that dispatches), read at trace time.
_mesh_lock = threading.Lock()
_MESHES: Dict[tuple, Mesh] = {}   # kubelint: guarded-by(_mesh_lock)


def mesh_key(mesh: Mesh) -> tuple:
    """Stable hashable identity of a mesh: axis layout + device ids +
    platform (two same-shape meshes over different chips must key — and
    so AOT-sign — distinctly)."""
    devs = tuple(int(d.id) for d in mesh.devices.flat)
    plat = mesh.devices.flat[0].platform
    return (tuple(mesh.shape.items()), devs, plat)


def register_mesh(mesh: Mesh) -> tuple:
    key = mesh_key(mesh)
    with _mesh_lock:
        _MESHES[key] = mesh  # kubelint: ignore[purity/global-mutate] trace-time mesh registry: written under _mesh_lock by the dispatch wrappers, read only at TRACE time to resolve the hashable static key back to its Mesh — never inside traced computation
    return key


def _get_mesh(key: tuple) -> Mesh:
    with _mesh_lock:
        return _MESHES[key]


def _rep_spec(tree):
    """Per-leaf replicated spec pytree (shard_map also takes prefixes,
    but an explicit per-leaf tree survives None-leaves and NamedTuples
    of pytrees uniformly)."""
    return jax.tree.map(lambda _: P(), tree)


def gang_surface(cfg, intra_batch_topology: bool, batch, mesh,
                 n_nodes: int, n_pods: int) -> str:
    """The static surface this (cfg, routing, batch, mesh) dispatches
    on.  "tiled" mirrors the Pallas supported surface
    (utils/pallas_backend.unsupported_reason): intra_batch_topology off,
    every score plugin in the plane family, no soft spread constraints
    in the batch (host-side numpy inspection — a device-array batch
    skips the check and its caller carries the term-free contract, which
    the scheduler's needs_topo gate does: soft-spread batches route
    intra_batch_topology=True and land on "replicated" here).  Both
    sharded axes must divide exactly — shard_map, unlike GSPMD, does
    not pad."""
    if intra_batch_topology:
        return "replicated"
    for name, _ in cfg.scores:
        if name not in PK.SUPPORTED_SCORES:
            return "replicated"
    sv = getattr(getattr(batch, "spread_soft", None), "valid", None)
    if isinstance(sv, np.ndarray) and bool(sv.any()):
        return "replicated"
    mp = mesh.shape[AXIS_PODS]
    mn = mesh.shape[AXIS_NODES]
    if n_pods % mp or n_nodes % mn:
        return "replicated"
    return "tiled"


# --------------------------------------------------------------------------
# gang


@functools.partial(jax.jit,
                   static_argnames=("cfg", "mesh_key", "max_rounds",
                                    "intra_batch_topology",
                                    "residual_window", "surface"))
def _shardmap_gang(cluster, batch, cfg, rng, mesh_key,
                   host_ok=None, score_bias=None,
                   max_rounds: Optional[int] = None,
                   intra_batch_topology: bool = True,
                   residual_window: int = 512,
                   surface: str = "replicated") -> GangResult:
    """The mesh gang jit root (one per (cfg, mesh, surface) static
    combination).  AOT seam name "_shardmap_gang"."""
    mesh = _get_mesh(mesh_key)
    if surface == "tiled":
        return _gang_tiled(cluster, batch, cfg, rng, mesh,
                           host_ok=host_ok, score_bias=score_bias,
                           max_rounds=max_rounds,
                           residual_window=residual_window)
    return _gang_replicated(cluster, batch, cfg, rng, mesh,
                            host_ok=host_ok, score_bias=score_bias,
                            max_rounds=max_rounds,
                            intra_batch_topology=intra_batch_topology,
                            residual_window=residual_window)


def _gang_replicated(cluster, batch, cfg, rng, mesh, host_ok, score_bias,
                     max_rounds, intra_batch_topology, residual_window):
    """Every device traces the single-device auction body on replicated
    inputs — bit-identity by construction (it IS _gang_program)."""
    dyn = {}
    if host_ok is not None:
        dyn["host_ok"] = host_ok
    if score_bias is not None:
        dyn["score_bias"] = score_bias

    def body(cl, b, r, dk):
        return gang._gang_program(
            cl, b, cfg, r, max_rounds=max_rounds,
            intra_batch_topology=intra_batch_topology,
            residual_window=residual_window, kernel_backend="lax", **dk)

    out_struct = jax.eval_shape(body, cluster, batch, rng, dyn)
    return shard_map(
        body, mesh,
        in_specs=(_rep_spec(cluster), _rep_spec(batch), P(),
                  _rep_spec(dyn)),
        out_specs=_rep_spec(out_struct),
        check_rep=False)(cluster, batch, rng, dyn)


def _gang_tiled(cluster, batch, cfg, rng, mesh, host_ok, score_bias,
                max_rounds, residual_window):
    """The gather-free tiled auction: Pallas-decomposition planes,
    node-axis collective stats, pods-axis all_gather resolution,
    replicated admission.  Bit-match oracle: models/gang.py's lax path
    at intra_batch_topology=False (the same contract — and largely the
    same math — as ops/pallas_kernels.propose)."""
    from ..models.batch import densify_for
    from ..models.programs import run_filters, static_raw_scores

    batch = densify_for(cluster, batch)
    B = batch.req.shape[0]
    N = cluster.allocatable.shape[0]
    R = cluster.allocatable.shape[1]
    Pn = batch.ports_hot.shape[1]
    if max_rounds is None:
        max_rounds = B
    filters = set(cfg.filters)
    use_fit = "NodeResourcesFit" in filters
    use_ports = "NodePorts" in filters
    use_window = bool(residual_window) and residual_window < B  # kubelint: ignore[host-sync/cast] trace-time constant: residual_window is a static int (jit static_argnames on _shardmap_gang)

    # ---- round-invariant precompute at GSPMD level: the static-filter
    # and raw-score kernel family lowers correctly on every supported
    # mesh shape (schedule_batch's ungated (2,4) equivalence is the
    # evidence); only the LOOP below needs the explicit program.
    static_ok, static_unres, _affinity_ok = run_filters(
        cluster, batch, cfg, host_ok,
        skip=("NodeResourcesFit", "NodePorts"))
    ports_ok0 = (K.node_ports_filter(cluster, batch) if use_ports
                 else jnp.ones((B, N), bool))
    score_pre = dict(static_raw_scores(cluster, batch, cfg))
    pod_idx = jnp.arange(B, dtype=jnp.int32)
    tie_keys = jax.vmap(lambda i: jax.random.fold_in(rng, i))(pod_idx)
    gumbel = jax.vmap(
        lambda k: jax.random.gumbel(k, (N,), jnp.float32))(tie_keys)
    bundle = PK.build_bundle(cluster, batch, cfg, static_ok, ports_ok0,
                             score_pre, score_bias, gumbel)

    mp = mesh.shape[AXIS_PODS]
    mn = mesh.shape[AXIS_NODES]
    Bl, Nl = B // mp, N // mn
    Z = bundle["zone"].shape[1]
    plane = {name: i
             for i, name in enumerate(PK.plane_order(
                 cfg, score_bias is not None))}
    scores_static = tuple((n, float(w)) for n, w in cfg.scores)  # kubelint: ignore[host-sync/cast] trace-time constant: weights are static ints from cfg.scores (jit static arg)

    def body(planes, mask_t, unres_t, breq, bnz, bports,
             basnode, ipa_any, skipb, validb, alloc, zone, nodev,
             req0, nz0):
        po = lax.axis_index(AXIS_PODS) * Bl
        no = lax.axis_index(AXIS_NODES) * Nl
        gum_t = planes[plane["gumbel"]]
        alloc_t = lax.dynamic_slice_in_dim(alloc, no, Nl)
        zone_t = lax.dynamic_slice_in_dim(zone, no, Nl)
        nv_t = lax.dynamic_slice_in_dim(nodev, no, Nl)
        breq_l = lax.dynamic_slice_in_dim(breq, po, Bl)
        bnz_l = lax.dynamic_slice_in_dim(bnz, po, Bl)
        bports_l = lax.dynamic_slice_in_dim(bports, po, Bl)
        skip_l = lax.dynamic_slice_in_dim(skipb, po, Bl)
        ipaany_l = lax.dynamic_slice_in_dim(ipa_any, po, Bl)
        valid_l = lax.dynamic_slice_in_dim(validb, po, Bl)
        has_zone = jnp.any(zone_t > 0, axis=1)   # [Nl]

        def feas_tile(c, live):
            """ops/pallas_kernels._make_kernel feas_tile, on the shard's
            tile: identical f32/bool op sequence (the oracle contract's
            'VPU recompute' half)."""
            f = mask_t & live[:, None]
            if use_fit:
                used_t = lax.dynamic_slice_in_dim(c["req"], no, Nl)
                pods_ok = (alloc_t[:, CH_PODS][None, :]
                           >= breq_l[:, CH_PODS][:, None]
                           + used_t[:, CH_PODS][None, :])
                res_ok = jnp.ones((Bl, Nl), bool)
                zero_req = jnp.ones((Bl,), bool)
                for r_ in range(R):
                    if r_ == CH_PODS:
                        continue
                    free_ok = (alloc_t[:, r_][None, :]
                               >= breq_l[:, r_][:, None]
                               + used_t[:, r_][None, :])
                    if r_ < N_FIXED_CHANNELS:
                        res_ok = res_ok & free_ok
                    else:
                        res_ok = res_ok & (free_ok
                                           | (breq_l[:, r_] <= 0)[:, None])
                    zero_req = zero_req & (breq_l[:, r_] == 0)
                f = f & pods_ok & (zero_req[:, None] | res_ok)
            if use_ports:
                pu_t = lax.dynamic_slice_in_dim(c["ports_used"], no, Nl)
                conflict = jnp.dot(bports_l, pu_t.T,
                                   preferred_element_type=jnp.float32) > 0.5
                f = f & ~conflict
            return f

        def resource_fracs(c):
            nz_t = lax.dynamic_slice_in_dim(c["nz"], no, Nl)
            req_cpu = nz_t[:, 0][None, :] + bnz_l[:, 0][:, None]
            req_mem = nz_t[:, 1][None, :] + bnz_l[:, 1][:, None]
            alloc_cpu = jnp.broadcast_to(alloc_t[:, CH_CPU][None, :],
                                         (Bl, Nl))
            alloc_mem = jnp.broadcast_to(alloc_t[:, CH_MEM][None, :],
                                         (Bl, Nl))
            return req_cpu, req_mem, alloc_cpu, alloc_mem

        def stats_for(f):
            """Phase-0 twin: per-pod normalization statistics, tile
            reduce + "nodes"-axis collective.  Float max/min are exactly
            associative; the DPS zone sums are integer-valued f32, exact
            under psum below 2**24 — the Pallas cross-tile argument,
            verbatim."""
            st = {}
            st["act"] = K.exact_pmax(
                jnp.max(f.astype(jnp.float32), axis=1), AXIS_NODES)
            names = {n for n, _ in scores_static}
            if "NodeAffinity" in names:
                raw = planes[plane["raw:NodeAffinity"]]
                st["max_na"] = K.exact_pmax(
                    jnp.max(jnp.where(f, raw, _NEG), axis=1), AXIS_NODES)
            if "TaintToleration" in names:
                raw = planes[plane["raw:TaintToleration"]]
                st["max_tt"] = K.exact_pmax(
                    jnp.max(jnp.where(f, raw, _NEG), axis=1), AXIS_NODES)
            if "InterPodAffinity" in names:
                raw = planes[plane["ipa_raw"]]
                st["max_ip"] = K.exact_pmax(
                    jnp.max(jnp.where(f, raw, _NEG), axis=1), AXIS_NODES)
                st["min_ip"] = K.exact_pmin(
                    jnp.min(jnp.where(f, raw, -_NEG), axis=1), AXIS_NODES)
            if "DefaultPodTopologySpread" in names:
                raw = planes[plane["dps_raw"]]
                st["max_dps"] = K.exact_pmax(
                    jnp.max(jnp.where(f, raw, _NEG), axis=1), AXIS_NODES)
                st["havez"] = K.exact_pmax(
                    jnp.max((f & has_zone[None, :]).astype(jnp.float32),
                            axis=1), AXIS_NODES)
                # integer-valued f32 counts: exact under psum below 2**24
                # (tools/kubeexact proves the bound at north-star shapes)
                st["czone"] = K.exact_psum(
                    jnp.dot(jnp.where(f, raw, 0.0), zone_t,
                            preferred_element_type=jnp.float32),
                    AXIS_NODES)
            return st

        def combine(c, f, st):
            """Phase-1 twin: the weighted score combine on the tile,
            same formula helpers, same accumulation order as
            run_scores/the Pallas kernel."""
            total = jnp.zeros((Bl, Nl), jnp.float32)
            for name, weight in scores_static:
                if name == "NodeResourcesBalancedAllocation":
                    s = K.balanced_formula(*resource_fracs(c))
                elif name == "NodeResourcesLeastAllocated":
                    rc, rm, ac, am = resource_fracs(c)
                    s = K._idiv(K.least_formula(rc, ac) * 1.0
                                + K.least_formula(rm, am) * 1.0, 2.0)
                elif name == "NodeResourcesMostAllocated":
                    rc, rm, ac, am = resource_fracs(c)
                    s = K._idiv(K.most_formula(rc, ac) * 1.0
                                + K.most_formula(rm, am) * 1.0, 2.0)
                elif name == "ImageLocality":
                    s = planes[plane["raw:ImageLocality"]]
                elif name == "NodePreferAvoidPods":
                    s = planes[plane["raw:NodePreferAvoidPods"]]
                elif name == "NodeAffinity":
                    raw = planes[plane["raw:NodeAffinity"]]
                    max_c = jnp.maximum(st["max_na"], 0.0)
                    scaled = K._idiv(MAX_NODE_SCORE * raw,
                                     jnp.maximum(max_c, 1.0)[:, None])
                    s = jnp.where((max_c > 0)[:, None], scaled, 0.0)
                elif name == "TaintToleration":
                    raw = planes[plane["raw:TaintToleration"]]
                    max_c = jnp.maximum(st["max_tt"], 0.0)
                    scaled = MAX_NODE_SCORE - K._idiv(
                        MAX_NODE_SCORE * raw,
                        jnp.maximum(max_c, 1.0)[:, None])
                    s = jnp.where((max_c > 0)[:, None], scaled,
                                  MAX_NODE_SCORE)
                elif name == "InterPodAffinity":
                    raw = planes[plane["ipa_raw"]]
                    max_c = jnp.maximum(st["max_ip"], 0.0)
                    min_c = jnp.minimum(st["min_ip"], 0.0)
                    diff = max_c - min_c
                    norm = jnp.where(
                        (diff > 0)[:, None],
                        K._idiv(MAX_NODE_SCORE * (raw - min_c[:, None]),
                                jnp.maximum(diff, 1.0)[:, None]), 0.0)
                    s = jnp.where(ipaany_l[:, None], norm, raw)
                elif name == "PodTopologySpread":
                    # no-soft-constraints constant path: exactly what a
                    # term-free batch evaluates to (the surface gate
                    # routes soft-spread batches to "replicated")
                    s = jnp.where(f, MAX_NODE_SCORE, 0.0)
                elif name == "DefaultPodTopologySpread":
                    raw = planes[plane["dps_raw"]]
                    max_node = jnp.maximum(st["max_dps"], 0.0)
                    f_score = jnp.where(
                        (max_node > 0)[:, None],
                        MAX_NODE_SCORE * (max_node[:, None] - raw)  # kubelint: ignore[numeric/score-div] reference computes fScore in float64 (default_pod_topology_spread.go:126); mirrors the lax/Pallas twin exactly
                        / jnp.maximum(max_node, 1.0)[:, None],
                        MAX_NODE_SCORE)
                    cz = st["czone"]
                    max_zone = jnp.maximum(jnp.max(cz, axis=1), 0.0)
                    nzc = jnp.dot(cz, zone_t.T,
                                  preferred_element_type=jnp.float32)
                    zone_score = jnp.where(
                        (max_zone > 0)[:, None],
                        MAX_NODE_SCORE * (max_zone[:, None] - nzc)  # kubelint: ignore[numeric/score-div] reference computes zoneScore in float64 (default_pod_topology_spread.go:142); mirrors the lax/Pallas twin exactly
                        / jnp.maximum(max_zone, 1.0)[:, None],
                        MAX_NODE_SCORE)
                    with_zone = (f_score * (1.0 - K.ZONE_WEIGHTING)
                                 + K.ZONE_WEIGHTING * zone_score)
                    havez = st["havez"] > 0
                    out = jnp.where(havez[:, None] & has_zone[None, :],
                                    with_zone, f_score)
                    out = jnp.floor(out)
                    s = jnp.where(skip_l[:, None], 0.0, out)
                else:  # pragma: no cover - gang_surface gates this
                    raise ValueError(
                        "shard_map tiled surface: unsupported score "
                        "kernel %s" % name)
                total = total + jnp.where(f, s, 0.0) * weight
            if "bias" in plane:
                total = total + planes[plane["bias"]]
            return total

        def round_t(c, in_window, windowed: bool):
            assigned_l = lax.dynamic_slice_in_dim(c["assigned"], po, Bl)
            live = (assigned_l < 0) & valid_l
            if in_window is not None:
                live = live & lax.dynamic_slice_in_dim(in_window, po, Bl)
            f = feas_tile(c, live)
            st = stats_for(f)
            total = combine(c, f, st)
            # gather-free cross-shard argmax, first-index tie-break:
            # per-tile gumbel decomposition then MIN global index among
            # exact (score, gumbel) ties — the earliest index IS
            # jnp.argmax's choice (blessed ops/kernels.py pair; the
            # Pallas kernel folds the same tuple across grid tiles)
            tile_best, tile_h, tile_arg = K.gumbel_tiebreak_argmax(
                total, f, gum_t, no, _NEG)
            best, gidx = K.crossaxis_first_index_argmax(
                tile_best, tile_h, tile_arg, AXIS_NODES, _NEG)
            active_l = st["act"] > 0
            prop_l = jnp.where(active_l, gidx, N).astype(jnp.int32)
            # collective host resolution: winners to every device, then
            # the IDENTICAL replicated O(B) admission everywhere
            prop = lax.all_gather(prop_l, AXIS_PODS, tiled=True)
            active = lax.all_gather(active_l, AXIS_PODS, tiled=True)
            bestg = lax.all_gather(best, AXIS_PODS, tiled=True)
            live_g = lax.all_gather(live, AXIS_PODS, tiled=True)

            admit = admission_mask(prop, active, breq, bports, basnode,
                                   alloc, c["req"], use_ports, N)
            add_req, add_nz, add_ports = admission_sums(
                admit, prop, breq, bnz, basnode, use_ports, N)
            new = dict(c)
            new["req"] = c["req"] + add_req
            new["nz"] = c["nz"] + add_nz
            if use_ports:
                new["ports_used"] = jnp.maximum(c["ports_used"], add_ports)
            new["assigned"] = jnp.where(admit, prop, c["assigned"])
            new["win_score"] = jnp.where(admit, bestg, c["win_score"])
            new["feas0"] = jnp.where(c["rounds"] == 0, f, c["feas0"])
            admitted_any = jnp.any(admit)
            new["rounds"] = c["rounds"] + 1
            new["admits"] = c["admits"] + admitted_any.astype(jnp.int32)
            if windowed:
                new_retire = (~active) & live_g & ~c["retired"]
                new["retired"] = jnp.where(
                    admitted_any, jnp.zeros_like(c["retired"]),
                    c["retired"] | new_retire)
                new["progress"] = admitted_any | jnp.any(new_retire)
            else:
                new["progress"] = admitted_any
            return new

        carry0 = dict(
            req=req0, nz=nz0,
            ports_used=jnp.zeros((N, Pn), jnp.float32),
            assigned=jnp.full((B,), -1, jnp.int32),
            win_score=jnp.zeros((B,), jnp.float32),
            feas0=jnp.zeros((Bl, Nl), bool),
            rounds=jnp.int32(0), admits=jnp.int32(0),
            progress=jnp.bool_(True),
            retired=jnp.zeros((B,), bool))

        if max_rounds < 1:
            out = carry0
        elif not use_window:
            def cond(c):
                return c["progress"] & (c["rounds"] < max_rounds)

            out = lax.while_loop(cond, lambda c: round_t(c, None, False),
                                 carry0)
        else:
            # phase A: one full-width round (windowed retirement
            # bookkeeping); phase B: rounds over the first
            # residual_window still-unassigned pods — selected by MASK,
            # not row-gather (a gather would reshuffle the pod shards
            # every round); non-window pods propose the no-op segment,
            # which leaves every other segment's prefix sums untouched,
            # so admission equals the gathered lax form exactly
            out = round_t(carry0, None, True)

            def condw(c):
                pool = (c["assigned"] < 0) & validb & ~c["retired"]
                return (c["progress"] & jnp.any(pool)
                        & (c["admits"] < max_rounds))

            def bodyw(c):
                pool = (c["assigned"] < 0) & validb & ~c["retired"]
                in_w = pool & (jnp.cumsum(pool.astype(jnp.int32))
                               <= residual_window)
                return round_t(c, in_w, True)

            out = lax.while_loop(condw, bodyw, out)

        f0 = out["feas0"]
        n_feas = lax.all_gather(
            K.exact_psum(jnp.sum(f0.astype(jnp.int32), axis=1),
                         AXIS_NODES),
            AXIS_PODS, tiled=True)
        base_t = nv_t[None, :] & valid_l[:, None]
        au_l = jnp.all(unres_t | f0 | ~base_t, axis=1)
        au_l = K.exact_pmin(au_l.astype(jnp.int32), AXIS_NODES) > 0
        all_unres = lax.all_gather(au_l, AXIS_PODS, tiled=True)
        return (out["assigned"], out["win_score"], out["rounds"],
                out["req"], out["nz"], out["ports_used"], f0, n_feas,
                all_unres)

    tile2 = P(AXIS_PODS, AXIS_NODES)
    (assigned, win_score, rounds, req, nz, ports_used, feas0, n_feas,
     all_unres) = shard_map(
        body, mesh,
        in_specs=(P(None, AXIS_PODS, AXIS_NODES), tile2, tile2,
                  P(), P(), P(), P(), P(), P(), P(), P(), P(), P(),
                  P(), P()),
        out_specs=(P(), P(), P(), P(), P(), P(), tile2, P(), P()),
        check_rep=False)(
        bundle["planes"], bundle["mask"], static_unres,
        bundle["breq"], bundle["bnz"], bundle["bports"],
        batch.ports_asnode_hot, bundle["ipa_any"], bundle["skip"],
        batch.valid, bundle["alloc"], bundle["zone"], cluster.node_valid,
        cluster.requested, cluster.nonzero_requested)

    packed = jnp.concatenate([assigned, n_feas,
                              all_unres.astype(jnp.int32),
                              rounds.reshape(1)])
    return GangResult(chosen=assigned, score=win_score, rounds=rounds,
                      requested=req, nz=nz, ports_used=ports_used,
                      feasible0=feas0, unresolvable=static_unres,
                      n_feasible=n_feas, all_unresolvable=all_unres,
                      packed=packed)


# --------------------------------------------------------------------------
# sequential


@functools.partial(jax.jit, static_argnames=("cfg", "mesh_key"))
def _shardmap_sequential(cluster, batch, cfg, rng, mesh_key,
                         hard_pod_affinity_weight=1.0, host_ok=None,
                         start_index=0, score_bias=None):
    """The mesh sequential jit root: the serial scan is replicated per
    device (its per-step work is O(N + T*L); the pod axis is serial BY
    CONSTRUCTION, so there is no cross-pod parallelism to shard —
    explicit replication is the correctness fix for the legacy
    partitioner's cross-shard index selection).  AOT seam name
    "_shardmap_sequential"."""
    mesh = _get_mesh(mesh_key)
    dyn = dict(hard_pod_affinity_weight=hard_pod_affinity_weight,
               start_index=start_index)
    if host_ok is not None:
        dyn["host_ok"] = host_ok
    if score_bias is not None:
        dyn["score_bias"] = score_bias

    def body(cl, b, r, dk):
        return sequential._sequential_program(cl, b, cfg, r, **dk)

    out_struct = jax.eval_shape(body, cluster, batch, rng, dyn)
    return shard_map(
        body, mesh,
        in_specs=(_rep_spec(cluster), _rep_spec(batch), P(),
                  _rep_spec(dyn)),
        out_specs=_rep_spec(out_struct),
        check_rep=False)(cluster, batch, rng, dyn)


# --------------------------------------------------------------------------
# delta scatter


def _cluster_specs(cluster):
    """Per-field PartitionSpec tree of the resident cluster's committed
    layout (parallel/mesh.py shard_cluster): node-axis tensors over
    "nodes", existing-pod tensors over "pods", term/vocab pytrees
    replicated."""
    from .mesh import NODE_AXIS_FIELDS, POD_AXIS_FIELDS
    out = {}
    for f in type(cluster)._fields:
        v = getattr(cluster, f)
        if f in NODE_AXIS_FIELDS:
            out[f] = P(AXIS_NODES)
        elif f in POD_AXIS_FIELDS:
            out[f] = P(AXIS_PODS)
        else:
            out[f] = jax.tree.map(lambda _: P(), v)
    return type(cluster)(**out)


def _apply_delta_body(cluster, delta, mesh_key):
    mesh = _get_mesh(mesh_key)
    specs = _cluster_specs(cluster)

    def body(cl, d):
        # shift the replicated global row tables into THIS shard's local
        # row space; rows owned by other shards (and the one-past-
        # capacity pads) map one past the LOCAL capacity, which the
        # scatter's mode="drop" discards — the pre-sharded twin of the
        # single-device scatter, field math shared verbatim
        nl = cl.allocatable.shape[0]
        pl_ = cl.pod_valid.shape[0]
        noff = lax.axis_index(AXIS_NODES) * nl
        poff = lax.axis_index(AXIS_PODS) * pl_
        nr = d.node_rows - noff
        nr = jnp.where((nr >= 0) & (nr < nl), nr, nl)
        pr = d.pod_rows - poff
        pr = jnp.where((pr >= 0) & (pr < pl_), pr, pl_)
        return programs._apply_cluster_delta(
            cl, d._replace(node_rows=nr, pod_rows=pr))

    return shard_map(body, mesh,
                     in_specs=(specs, _rep_spec(delta)),
                     out_specs=specs, check_rep=False)(cluster, delta)


_shardmap_apply_delta_donated = jax.jit(
    _apply_delta_body, static_argnames=("mesh_key",), donate_argnums=(0,))
_shardmap_apply_delta_shared = jax.jit(
    _apply_delta_body, static_argnames=("mesh_key",))


def apply_cluster_delta_mesh(cluster, delta, mesh, donate: bool = True):
    """Pre-sharded resident scatter: apply a ClusterDelta to the sharded
    resident WITHOUT the legacy partitioner — each shard scatters its
    locally-owned rows (node AND pod axis).  Falls back to the GSPMD
    lowering when an axis does not divide the mesh (shard_map cannot
    pad); node-axis-only meshes divide trivially on the pod axis."""
    import jax.numpy as jnp  # noqa: F811 - local alias mirrors delta.py

    from . import mesh as pmesh
    mp = mesh.shape[AXIS_PODS]
    mn = mesh.shape[AXIS_NODES]
    n_nodes = int(cluster.allocatable.shape[0])
    n_pods = int(cluster.pod_valid.shape[0])
    if n_nodes % mn or n_pods % mp:
        return pmesh.sharded_apply_cluster_delta(cluster, delta, mesh,
                                                 donate=donate,
                                                 partitioner="gspmd")
    key = register_mesh(mesh)
    delta = pmesh.replicate(jax.tree.map(jnp.asarray, delta), mesh)
    fn = (_shardmap_apply_delta_donated if donate
          else _shardmap_apply_delta_shared)
    return fn(cluster, delta, mesh_key=key)


# --------------------------------------------------------------------------
# dispatch wrappers (the parallel/mesh.py sharded_* entries route here)


def schedule_gang_mesh(cluster, batch, cfg, rng, mesh,
                       shard_existing_pods: bool = True,
                       max_rounds: Optional[int] = None,
                       host_ok=None, intra_batch_topology: bool = True,
                       score_bias=None,
                       residual_window: int = 512) -> GangResult:
    """Gang auction over the mesh via the explicit shard_map program.
    Placement mirrors the GSPMD entry (shard_cluster/shard_batch commit
    the inputs); the AOT seam keys on (cfg, mesh_key, surface)."""
    from ..utils import aot
    from . import mesh as pmesh
    if cfg.percentage_of_nodes_to_score != 100:
        # the auction needs the global view; normalize the static out of
        # the program key exactly like gang.schedule_gang
        cfg = cfg._replace(percentage_of_nodes_to_score=100)
    n_nodes = int(cluster.allocatable.shape[0])
    n_pods = int(batch.valid.shape[0])
    surface = gang_surface(cfg, intra_batch_topology, batch, mesh,
                           n_nodes, n_pods)
    key = register_mesh(mesh)
    cluster = pmesh.shard_cluster(cluster, mesh, shard_existing_pods)
    batch = pmesh.shard_batch(batch, mesh)
    rng = pmesh._put(rng, NamedSharding(mesh, P()))
    host_ok = pmesh._shard_host_ok(host_ok, mesh)
    score_bias = pmesh._shard_host_ok(score_bias, mesh)
    with pmesh.ambient_mesh(mesh):
        return aot.dispatch(
            "_shardmap_gang", _shardmap_gang,
            (cluster, batch, cfg, rng),
            dict(mesh_key=key, host_ok=host_ok, score_bias=score_bias,
                 max_rounds=max_rounds,
                 intra_batch_topology=intra_batch_topology,
                 residual_window=residual_window, surface=surface),
            static_argnums=(2,),
            static_argnames=("mesh_key", "max_rounds",
                             "intra_batch_topology", "residual_window",
                             "surface"))


def schedule_sequential_mesh(cluster, batch, cfg, rng, mesh,
                             shard_existing_pods: bool = True,
                             hard_pod_affinity_weight: float = 1.0,
                             host_ok=None, start_index=0,
                             score_bias=None):
    """Sequential replay over the mesh via the explicit shard_map
    program (replicated scan body; see _shardmap_sequential)."""
    from ..utils import aot
    from . import mesh as pmesh
    key = register_mesh(mesh)
    cluster = pmesh.shard_cluster(cluster, mesh, shard_existing_pods)
    batch = pmesh.shard_batch(batch, mesh)
    rng = pmesh._put(rng, NamedSharding(mesh, P()))
    host_ok = pmesh._shard_host_ok(host_ok, mesh)
    score_bias = pmesh._shard_host_ok(score_bias, mesh)
    with pmesh.ambient_mesh(mesh):
        return aot.dispatch(
            "_shardmap_sequential", _shardmap_sequential,
            (cluster, batch, cfg, rng),
            dict(mesh_key=key,
                 hard_pod_affinity_weight=hard_pod_affinity_weight,
                 host_ok=host_ok, start_index=start_index,
                 score_bias=score_bias),
            static_argnums=(2,),
            static_argnames=("mesh_key",))
