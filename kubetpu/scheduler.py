"""Scheduler core: the serving loop tying queue, cache, framework and the
device programs together.

reference: pkg/scheduler/scheduler.go (Scheduler :69, New :210, Run :339,
scheduleOne :509, assume :435, bind :457, recordSchedulingFailure :391,
skipPodSchedule :391) and pkg/scheduler/eventhandlers.go (addAllEventHandlers
:362).  The reference schedules one pod per cycle; this scheduler pops a
BATCH from the queue and runs the whole batch through one jitted
sequential-replay program (kubetpu/models/sequential.py), preserving the
serial semantics (pod i sees placements 0..i-1) while amortizing all host
work — the design lever named in SURVEY.md §7 step 2.

Cycle pipeline (mirroring scheduleOne's phases):
  pop batch -> snapshot (incremental) -> tensorize -> PreFilter(host) +
  host filter masks -> DEVICE filter+score+select (scan) ->
  per pod: Reserve -> assume -> Permit -> async bind cycle
  (WaitOnPermit -> PreBind -> Bind -> FinishBinding -> PostBind)
with failures flowing through Unreserve -> ForgetPod ->
recordSchedulingFailure exactly like the reference (scheduler.go:586-687).
"""

from __future__ import annotations

import copy
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from .api import types as api
from .apis.config import (KubeSchedulerConfiguration, KubeSchedulerProfile)
from .client.store import ClusterStore
from .framework import interface as fw
from .framework.interface import Code, CycleState, Status
from .framework.runtime import Framework
from .framework.types import NodeInfo, PodInfo, QueuedPodInfo
from .models import programs
from .models.batch import PodBatchBuilder
from .models.sequential import schedule_sequential
from .plugins.intree import new_in_tree_registry
from .schedqueue.queue import SchedulingQueue
from .state.cache import SchedulerCache, Snapshot
from .state.delta import DeltaTensorizer
from .state.tensors import SnapshotBuilder
from .utils import chaos as uchaos
from .utils import devstats as udevstats
from .utils import journal as ujournal
from .utils import slo as uslo
from .utils import telemetry as utelemetry
from .utils import trace as utrace
from .utils.decisions import DecisionLog, PodDecision
from .utils.trace import Trace


def _vocab_caps(table):
    """Tensor-width signature chained cycles compare to detect overflow
    (tensor shapes would change) — ONE definition shared with the
    DeltaTensorizer's resync guard, see state/tensors.vocab_signature."""
    from .state.tensors import vocab_signature
    return vocab_signature(table)


@dataclass
class ScheduleOutcome:
    pod: api.Pod
    node: str = ""                 # "" => unschedulable
    err: Optional[str] = None
    n_feasible: int = 0
    preemption_may_help: bool = True


@dataclass
class PreparedCycle:
    """Host-side state of one scheduling cycle between tensorize and
    commit — the unit the pipelined drain keeps in flight."""
    fwk: "Framework"
    trace: Trace
    chain_seq0: int
    node_infos: list
    states: Dict[str, CycleState]
    live: list
    pinfos: list
    builder: SnapshotBuilder
    cluster: object
    batch: object
    host_relevant: Dict[str, bool]
    host_ok_dev: object
    cfg: programs.ProgramConfig
    cycle_ctx: object
    needs_topo: bool = True
    used_chain: bool = False
    chain_pod_uids: list = field(default_factory=list)
    score_bias: object = None   # [B, N] weighted host Score plugin totals
    # per-pod host-filter rejection reasons (uid -> reason -> node count),
    # folded into the DecisionLog by the commit-path audit
    host_reject: Dict[str, Dict[str, int]] = field(default_factory=dict)
    # the cycle's host-plugin relevance map (_host_relevance) — kept so a
    # scatter recovery's re-prepare never re-walks the plugin predicates
    relevance: Optional[Dict[str, Tuple[bool, bool]]] = None
    # wall-clock of the device dispatch start — the deadline guard
    # measures dispatch-to-readback against it (0.0 = never dispatched)
    dispatch_t0: float = 0.0
    # CompileTimer snapshot taken at dispatch_t0 (deadline armed only):
    # a cycle with any compile/cache-load activity is exempt from the
    # deadline, so a first-compile of a new pod bucket — legitimate,
    # bounded work — can never trip it and demote a healthy backend
    compile_snap: Optional[dict] = None
    # host-side seconds spent inside this cycle's dispatch->readback
    # window on OTHER work (the pipelined drain runs k-1's commit loop
    # there) — subtracted before the deadline comparison
    host_exempt_s: float = 0.0
    # wall-clock when this cycle was parked in the pipeline's in-flight
    # ring: caller think time between schedule_pending calls is host
    # time too, and must not count against the dispatch deadline (a
    # device hang still counts — it blocks the READBACK, which runs
    # after pickup)
    parked_t: float = 0.0
    # packed-readback completion time + the readback's device wait — the
    # SLO layer's commit-stage anchor and per-pod device share (stamped
    # unconditionally in _readback_group: two float stores, no clock call
    # beyond the one the wait measurement already makes)
    readback_done_t: float = 0.0
    device_wait: float = 0.0
    # cycle-journal capture (utils/journal.py, armed only): the cycle's
    # cluster-input provenance — ("resync"|"delta"|"noop", payload) from
    # the DeltaTensorizer seam or ("chain", pads) for chained cycles —
    # plus the RNG fold counter and sequential start index the dispatch
    # consumed, and the pipeline ring slot the cycle parked in
    journal_input: Optional[tuple] = None
    journal_rng: int = 0
    journal_start: int = 0
    ring_slot: int = 0
    # devstats deep-timing marker (utils/devstats.py): True when this
    # cycle's dispatch was micro-fenced — the commit side then pairs
    # the cycle's analytic FLOP count with the measured device seconds.
    # The fence's own seconds ride along explicitly: at sampling
    # intervals below the pipeline depth, newer samples land before
    # this cycle's commit runs, so "the program's last sample" would be
    # the wrong one
    devstats_fenced: bool = False
    devstats_fence_s: float = 0.0
    # DOUBLE-BUFFERED batch transfer (mesh serving): the sharded device
    # copy of `batch`, upload STARTED at prepare time so the host->device
    # transfer of wave k+1 overlaps wave k's auction on the device
    # (device_put is async; the tunnel serves the transfer behind the
    # queued auction program).  _dispatch_group consumes it instead of
    # re-uploading; None on single-chip profiles
    batch_dev: object = None


class Scheduler:
    """reference: scheduler.go:69."""

    def __init__(self, store: ClusterStore,
                 config: Optional[KubeSchedulerConfiguration] = None,
                 registry=None, seed: int = 0, async_binding: bool = True,
                 metrics=None, recorder=None):
        # warm restarts must not recompile byte-identical programs — the
        # persistent cache is a serving default, not a bench trick
        from .utils.compilation import enable_persistent_cache
        enable_persistent_cache()
        # KUBETPU_AOT_DIR: arm the serialized-executable runtime so prewarm
        # can deserialize build-time artifacts instead of tracing (falls
        # back silently on env mismatch — the trace path always works)
        from .utils import aot as _aot
        _aot.maybe_arm_from_env()
        # KUBETPU_CHAOS: arm the fault-injection registry (utils/chaos.py);
        # disarmed (the default) every injection site is one attribute read
        uchaos.maybe_arm_from_env()
        # KUBETPU_SLO: arm the per-pod latency SLO tracker (utils/slo.py);
        # disarmed (the default) every seam is one attribute read and the
        # hot path takes zero new locks (tests/test_slo.py poison test)
        uslo.maybe_arm_from_env()
        # KUBETPU_JOURNAL=<dir>: arm the durable cycle journal
        # (utils/journal.py) — every committed cycle appends one
        # self-contained replayable record; disarmed, every seam is one
        # attribute read (tests/test_journal.py poison test)
        ujournal.maybe_arm_from_env()
        # KUBETPU_DEVSTATS: arm device-side observability
        # (utils/devstats.py) — sampled per-program device-time fences,
        # the HBM residency ledger, roofline attribution; disarmed,
        # every seam is one attribute read and placements are
        # bit-identical armed vs disarmed (tests/test_devstats.py)
        udevstats.maybe_arm_from_env()
        # KUBETPU_TELEMETRY: arm the windowed sustained-load telemetry
        # ring (utils/telemetry.py) — the serving loop rolls one window
        # record per KUBETPU_TELEMETRY_WINDOW seconds; disarmed, the
        # tick seam is one attribute read (tests/test_telemetry.py)
        utelemetry.maybe_arm_from_env()
        import jax
        self.store = store
        self.config = config or KubeSchedulerConfiguration(
            profiles=[KubeSchedulerProfile()])
        if not self.config.profiles:
            self.config.profiles = [KubeSchedulerProfile()]
        self.metrics = metrics
        if recorder is None:
            # reference: profile/profile.go:33 NewRecorderFactory — every
            # profile gets a real recorder; the store plays the event sink
            from .utils.events import EventBroadcaster
            self.broadcaster = EventBroadcaster(sink=store)
            recorder = self.broadcaster.new_recorder()
        self.recorder = recorder or None
        self.cache = SchedulerCache(
            expire_listener=lambda pod: self._mark_chain_dirty())
        registry = registry or new_in_tree_registry()
        # plugin-EXISTENCE validation happens HERE, against the MERGED
        # registry (out-of-tree plugins included) — the reference rejects
        # unknown plugins at framework build time (framework.go:205);
        # config load validates everything else
        from .apis.load import validate as validate_config
        validate_config(self.config, registry_names=set(registry))

        # one framework per profile (reference: profile/profile.go:59 Map)
        self.profiles: Dict[str, Framework] = {}
        for prof in self.config.profiles:
            self.profiles[prof.scheduler_name] = Framework(
                registry, prof, client=store, metrics=metrics)

        from .extender import HTTPExtender
        self.extenders = [HTTPExtender(e) for e in self.config.extenders]

        any_fw = next(iter(self.profiles.values()))
        self.queue = SchedulingQueue(
            sort_key=any_fw.queue_sort_key,
            pod_initial_backoff=self.config.pod_initial_backoff_seconds,
            pod_max_backoff=self.config.pod_max_backoff_seconds,
            metrics=metrics)
        self.snapshot = Snapshot()
        self._rng_counter = seed
        # rotating node-search start (reference: nextStartNodeIndex,
        # generic_scheduler.go:451); persists across cycles
        self._next_start_node_index = 0
        # cycle chaining (SURVEY §7 delta updates): in gang mode the
        # auction's materialized cluster IS the next cycle's snapshot
        # tensors, so successive drain cycles skip the full re-tensorize.
        # Any store event the chain does not account for (node changes,
        # external binds, deletions) marks it dirty -> full rebuild.
        # written by bind threads (_forget) racing the serving thread
        self._chain = None  # dict(builder, cluster, pod_uids, caps)  # kubelint: guarded-by(_chain_lock)
        # monotonic event sequence: handlers bump it AFTER mutating the
        # cache.  The scheduler captures the sequence BEFORE snapshotting,
        # so "bump visible in the capture" implies "mutation visible to the
        # snapshot"; a mutation whose bump lands after the capture makes
        # the chain's stored sequence stale at its next use — the race can
        # only over-invalidate, never miss an event
        self._chain_seq = 0
        self._chain_lock = threading.Lock()
        # device mesh for the serving path: mesh_shape=(pods, nodes) runs
        # every cycle's program through parallel/mesh.py sharding (the
        # reference's 16-goroutine parallelizer runs on every cycle,
        # internal/parallelize/parallelism.go:26-43); None = single device
        self._mesh = None
        if self.config.mesh_shape:
            from .parallel import mesh as pmesh
            self._mesh = pmesh.make_mesh(tuple(self.config.mesh_shape))
        self._jax = jax
        # cumulative wall time spent blocked on the per-cycle packed
        # readback — the only point where device completion is observable
        # (block_until_ready does not block through the axon tunnel);
        # benchmarks read this for the honest host/device split
        self.device_wait_s = 0.0
        # committed scheduling cycles (benchmark/diagnostics surface — the
        # perf harness reports it next to device_wait_s)
        self.cycle_count = 0
        # auction round count of the most recent gang cycle (diagnostics)
        self.last_gang_rounds = 0
        # cumulative analytic device FLOPs (utils/flops.py; gang mode only)
        self.device_flops = 0.0
        self._async_binding = async_binding
        # per-pod decision audit (utils/decisions.py): bounded, on by
        # default, disabled with KUBETPU_AUDIT=0 — disabled, no commit
        # path takes its lock
        self.decisions = DecisionLog()
        # flight-recorder drop count already folded into the metrics
        # counter (serving thread only)
        self._flight_dropped_seen = 0
        # (failed-uid set, audit rows) of the last decision audit — the
        # retry-churn dedup in _commit_group (serving thread only)
        self._audit_cache = None
        # incremental tensorization (state/delta.py): one device-resident
        # cluster per profile, updated by bounded scatters; the full
        # rebuild is demoted to its anti-entropy resync (serving thread
        # only, like _audit_cache)
        self._delta: Dict[str, DeltaTensorizer] = {}
        # prepared-but-not-yet-dispatched cycles whose double-buffered
        # batch upload is in flight (mesh serving): their dispatch will
        # still READ the resident cluster, so the delta scatter's
        # donation is withheld while any of them exists —
        # DeltaTensorizer.safe_to_donate stays the single gate, this
        # list just joins the in-flight ring in feeding it.  Serving
        # thread only (appended in _prepare_group, removed at dispatch
        # or discard)
        self._undispatched: List[PreparedCycle] = []
        # delta telemetry for bench/perf: updated-row counts of recent
        # delta cycles (bounded ring) + monotonic tallies so windowed
        # readers survive ring eviction (serving thread only)
        from collections import deque
        self.delta_rows = deque(maxlen=4096)
        self.delta_cycle_count = 0
        self.resync_count = 0
        # self-healing runtime: the dispatch deadline (0 = off; env
        # overrides config so an operator can arm it on a live fleet),
        # the recovery audit trail (serving thread only, like
        # _audit_cache), and the chaos fire counts already folded into
        # scheduler_faults_injected_total
        import os as _os
        _dl = _os.environ.get("KUBETPU_DISPATCH_DEADLINE")
        self._dispatch_deadline = (
            float(_dl) if _dl
            else float(getattr(self.config, "dispatch_deadline_seconds",
                               0.0) or 0.0))
        # bounded like delta_rows: a persistent fault must not grow a
        # serving daemon's memory one incident dict per cycle forever
        self.recovery_log: deque = deque(maxlen=256)
        self._chaos_seen: Dict[str, int] = {}
        # journal counters already folded into the scheduler_journal_*
        # metrics (serving thread only, like _chaos_seen)
        self._journal_seen = (0, 0)   # (records_total, dropped_total)
        # PROFILES whose discarded pipelined cycle consumed a
        # delta/resync journal capture that will never be journaled
        # (chain-break re-prepare, scatter recovery): that profile's
        # resident has advanced past what the journal stream describes,
        # so its next journaled cycle must re-anchor from the mirror or
        # replay silently diverges.  Per-profile (each profile owns its
        # own DeltaTensorizer lineage — another profile's cycle must not
        # consume the flag); serving thread only
        self._journal_force_anchor: set = set()
        # deadline grace: cycles exempt from the deadline right after a
        # recovery — the recovery itself invalidates residents and can
        # change the traced program (demotion, new pod bucket), so the
        # next dispatch legitimately pays resync/compile cost; without
        # the grace a recovery could trip the deadline it just served
        # and requeue forever (serving thread only)
        self._deadline_grace = 0
        # pipelined drain (kubetpu/pipeline.py): the depth-k executor
        # owning the bounded ring of dispatched-but-uncommitted cycles.
        # Depth 1 = synchronous, 2 = the historical double-buffered
        # chain (the default), k parks up to k-1 cycles between calls.
        # Env override so an operator can re-depth a live fleet.
        from .pipeline import PipelinedExecutor, depth_from_env
        self._pipeline = PipelinedExecutor(
            self, depth_from_env(
                getattr(self.config, "pipeline_depth", 2) or 2))
        # last committed cycle's commit-failure flag (serving thread
        # only): a failed commit invalidates the speculative chain and
        # every in-flight cycle dispatched against it
        self._last_commit_failed = False
        # devstats chain-ledger memo (serving thread only): the chain
        # registration re-runs only when (profile, pads, n_nodes)
        # change — re-walking identical shapes every chained cycle
        # would tax the armed serving thread for nothing
        self._chain_ledger_key = None
        # (pod-axis bucket, compile-or-load seconds) per prewarmed program
        self.prewarm_report: List[Tuple[int, float]] = []
        self._bind_pool = ThreadPoolExecutor(max_workers=16,
                                             thread_name_prefix="binder")
        self._inflight_binds: List = []
        self._stop = threading.Event()
        self._serve_thread: Optional[threading.Thread] = None
        self._closed = False
        self._add_all_event_handlers()
        # reference: scheduler.go:548 — preemption runs unless disabled
        # (DisablePreemption componentconfig field)
        if getattr(self.config, "disable_preemption", False):
            self.preemptor = None
        else:
            from .preemption import Preemptor
            self.preemptor = Preemptor(self)
        # preemption is served through the PostFilter extension point
        # (DefaultPreemption); the Preemptor instance is late-bound because
        # it needs the scheduler itself
        from .plugins.intree import DefaultPreemption
        for fwk in self.profiles.values():
            for p in fwk.post_filter_plugins:
                if isinstance(p, DefaultPreemption):
                    p.preemptor = self.preemptor

    # ------------------------------------------------------------------ events

    def _add_all_event_handlers(self) -> None:
        """reference: eventhandlers.go:362 addAllEventHandlers."""
        s = self.store

        def on_pod(event: str, old, new) -> None:
            pod = new if new is not None else old
            if event == "add":
                if pod.spec.node_name:
                    self._add_pod_to_cache(pod)
                    self._mark_chain_dirty()   # external bound add
                elif self._responsible(pod):
                    self.queue.add(pod)
            elif event == "update":
                was_assigned = bool(old.spec.node_name)
                is_assigned = bool(new.spec.node_name)
                if is_assigned and not was_assigned:
                    # bind confirmed (possibly our own optimistic assume)
                    foreign = not self.cache.is_assumed_pod(new)
                    self._add_pod_to_cache(new)
                    if foreign:
                        self._mark_chain_dirty()   # a foreign writer bound it
                    self.queue.delete(old)
                    self.queue.assigned_pod_added(new)
                elif is_assigned:
                    self._update_pod_in_cache(old, new)
                    self._mark_chain_dirty()
                    self.queue.assigned_pod_updated(new)
                elif self._responsible(new) and not self._skip_pod_update(old, new):
                    self.queue.update(old, new)
            elif event == "delete":
                if pod.spec.node_name:
                    try:
                        self.cache.remove_pod(pod)
                    except ValueError:
                        pass
                    self._mark_chain_dirty()
                    self.queue.move_all_to_active_or_backoff_queue("PodDelete")
                else:
                    self.queue.delete(pod)
                    fwk = self.profiles.get(pod.spec.scheduler_name)
                    if fwk is not None:
                        fwk.reject_waiting_pod(pod.uid)

        def on_node(event: str, old, new) -> None:
            if event == "add":
                self.cache.add_node(new)
                self._mark_chain_dirty()
                self.queue.move_all_to_active_or_backoff_queue("NodeAdd")
            elif event == "update":
                self.cache.update_node(old, new)
                self._mark_chain_dirty()
                if self._node_scheduling_properties_changed(old, new):
                    self.queue.move_all_to_active_or_backoff_queue("NodeUpdate")
            elif event == "delete":
                try:
                    self.cache.remove_node(old)
                except ValueError:
                    pass
                self._mark_chain_dirty()

        def on_moveable(kind: str):
            def handler(event: str, old, new) -> None:
                self.queue.move_all_to_active_or_backoff_queue(f"{kind}{event.title()}")
            return handler

        s.subscribe("Pod", on_pod)
        s.subscribe("Node", on_node)
        for kind in ("PersistentVolume", "PersistentVolumeClaim",
                     "StorageClass", "Service", "CSINode"):
            s.subscribe(kind, on_moveable(kind))

    def _mark_chain_dirty(self) -> None:
        """Bump the chain event sequence AFTER the cache mutation it
        describes (capture happens before the snapshot, so this ordering
        guarantees a counted bump's mutation is snapshot-visible; a
        late bump only over-invalidates)."""
        with self._chain_lock:
            self._chain_seq += 1

    def _drop_chain_residency(self) -> None:
        """Residency-ledger seam (utils/devstats.py): the speculative
        chain was discarded, so its materialized cluster is no longer
        device-resident — the capacity planner must stop counting it.
        Disarmed: one attribute read.  Called OUTSIDE _chain_lock (the
        devstats lock never nests with it)."""
        ds = udevstats.devstats()
        if ds is not None:
            ds.drop_group("chain")
            self._chain_ledger_key = None

    def _chain_enabled(self, fwk) -> bool:
        # mesh profiles chain too (PR 14): materialize_assigned is a
        # concat/pad/scatter program — the kernel class the partitioner
        # lowers correctly at every mesh shape (unlike the auction loop,
        # which needed the explicit shard_map rewrite) — and without the
        # chain the depth-k executor serializes on mesh profiles, which
        # would leave the double-buffered batch upload nothing to
        # overlap with
        return (self.config.mode == "gang"
                and getattr(self.config, "chain_cycles", False))

    def _add_pod_to_cache(self, pod: api.Pod) -> None:
        try:
            self.cache.add_pod(pod)
        except ValueError:
            # already assumed on another node etc. — cache resolves
            pass

    def _update_pod_in_cache(self, old: api.Pod, new: api.Pod) -> None:
        try:
            self.cache.update_pod(old, new)
        except ValueError:
            self._add_pod_to_cache(new)

    def _responsible(self, pod: api.Pod) -> bool:
        # reference: eventhandlers.go:333 responsibleForPod
        return pod.spec.scheduler_name in self.profiles

    @staticmethod
    def _skip_pod_update(old: api.Pod, new: api.Pod) -> bool:
        """reference: eventhandlers.go:311 skipPodUpdate — only
        resourceVersion/status-ish changes."""
        return (old.spec == new.spec
                and old.metadata.labels == new.metadata.labels
                and old.metadata.annotations == new.metadata.annotations)

    @staticmethod
    def _node_scheduling_properties_changed(old: api.Node, new: api.Node) -> bool:
        # reference: eventhandlers.go:471
        return (old.spec.unschedulable != new.spec.unschedulable
                or old.metadata.labels != new.metadata.labels
                or old.spec.taints != new.spec.taints
                or old.status.allocatable != new.status.allocatable)

    # ------------------------------------------------------------------ cycle

    def _next_rng(self):
        self._rng_counter += 1
        return self._jax.random.PRNGKey(self._rng_counter)

    def schedule_pending(self, max_batch: Optional[int] = None,
                         timeout: float = 0.0) -> List[ScheduleOutcome]:
        """Run ONE batched scheduling cycle: pop up to batch_size pods and
        schedule them.  Returns outcomes (the test/introspection surface).
        The serving loop (run/serve_forever) just calls this repeatedly."""
        # telemetry tick seam: disarmed this is ONE attribute read (the
        # house contract); armed, the deadline check is one float
        # compare and a roll happens once per window, not per cycle
        tel = utelemetry.ring()
        if tel is not None:
            tel.maybe_tick(self)
        max_batch = max_batch or self.config.batch_size
        if self.extenders:
            # extenders are a per-pod HTTP round trip; keep the reference's
            # strictly serial semantics (scheduler.go:510 pops one pod)
            max_batch = 1
        if (self.config.pipeline_cycles and not self.extenders
                and self.config.mode == "gang"
                and getattr(self.config, "chain_cycles", False)):
            # the depth-k pipelined executor (kubetpu/pipeline.py):
            # prepare(k+1) overlaps device(k) and commit/bind(k-1)
            return self._pipeline.drain(max_batch, timeout)
        batch = self.queue.pop_batch(max_batch, timeout=timeout)
        if not batch:
            return []
        return self._schedule_batch(batch)

    def flush_pipeline(self) -> List[ScheduleOutcome]:
        """Commit every in-flight pipelined cycle, oldest first (used at
        shutdown and by callers that need every outcome materialized
        now)."""
        return self._pipeline.flush()

    def _schedule_batch(self, qpods: List[QueuedPodInfo]) -> List[ScheduleOutcome]:
        start = utrace.wallclock()
        # group by profile: one device program per framework config
        outcomes: List[ScheduleOutcome] = []
        by_profile: Dict[str, List[QueuedPodInfo]] = {}
        for qp in qpods:
            if self._skip_pod_schedule(qp.pod):
                continue
            by_profile.setdefault(qp.pod.spec.scheduler_name, []).append(qp)
        for name, group in by_profile.items():
            fwk = self.profiles[name]
            outcomes.extend(self._schedule_group(fwk, group))
        if self.metrics:
            self.metrics.observe_cycle(len(outcomes),
                                       utrace.wallclock() - start)
        return outcomes

    def _skip_pod_schedule(self, pod: api.Pod) -> bool:
        """reference: scheduler.go:691 skipPodSchedule — deleted or
        assumed-and-updated-only pods."""
        current = self.store.get_pod(pod.namespace, pod.metadata.name)
        if current is None or current.metadata.deletion_timestamp is not None:
            return True
        if self.cache.is_assumed_pod(pod):
            return True
        return False

    def _schedule_group(self, fwk: Framework,
                        qpods: List[QueuedPodInfo]) -> List[ScheduleOutcome]:
        prep, outcomes = self._prepare_group(fwk, qpods)
        if prep is None:
            return outcomes
        if self.extenders:
            try:
                return outcomes + self._schedule_with_extenders(
                    fwk, prep.live, prep.states, prep.node_infos,
                    prep.cluster, prep.batch, prep.cfg, prep.host_ok_dev,
                    prep.cycle_ctx, score_bias=prep.score_bias)
            finally:
                prep.trace.finish()
        with prep.trace.stage("dispatch"):
            try:
                res = self._dispatch_group(prep)
            except Exception as e:  # device/backend fault: recover, never
                # lose the batch (the old behavior leaked the popped pods
                # when the serving loop swallowed the exception)
                out = self._recover_cycle(prep, repr(e), "dispatch-error")
                prep.trace.finish(recovered="dispatch-error")
                return outcomes + out
        return outcomes + self._finish_group(prep, res)

    @staticmethod
    def _host_relevance(fwk: Framework, qpods: List[QueuedPodInfo]
                        ) -> Dict[str, Tuple[bool, bool]]:
        """ONE walk of the host filter plugins' relevance predicates per
        pod: uid -> (any relevant, any relevant beyond the device-covered
        volume family).  The walk is measurable at 4k pods/cycle, so every
        consumer — the pipelined drain's serialize decision, the host-mask
        loop gate, and the commit-time re-check — shares this map instead
        of re-walking (the round-5 ADVICE double-walk finding)."""
        from .state.volumes import DEVICE_COVERED_PLUGINS
        out: Dict[str, Tuple[bool, bool]] = {}
        for qp in qpods:
            rel = unc = False
            for p in fwk.host_filter_plugins:
                if fwk._relevant(p, qp.pod):
                    rel = True
                    if p.name() not in DEVICE_COVERED_PLUGINS:
                        unc = True
                        break
            out[qp.pod.uid] = (rel, unc)
        return out

    def _prepare_group(self, fwk: Framework, qpods: List[QueuedPodInfo],
                       uncommitted: Optional[List[PreparedCycle]] = None,
                       relevance: Optional[Dict[str, Tuple[bool, bool]]]
                       = None):
        """Host half of a cycle, up to (but excluding) the device dispatch:
        snapshot, PreFilter, tensorize-or-chain, host filter masks,
        nominated overlay.  Returns (PreparedCycle | None, early outcomes).
        uncommitted: EVERY dispatched-but-uncommitted pipelined cycle (the
        depth-k executor's in-flight ring) whose device buffers must
        survive this prepare (gates delta donation)."""
        # queue depths ride the cycle record; the read takes the queue's
        # condition lock, so it is GATED on the recorder being armed (the
        # disarmed hot path must take no new locks)
        depths = (self.queue.depths()
                  if utrace.flight_recorder() is not None else None)
        trace = Trace("Scheduling", profile=fwk.profile_name,
                      pods=len(qpods), queue_depths=depths)
        # devstats cycle tick: every Nth cycle is a deep-timing cycle —
        # its device dispatches (delta scatter below, the auction in
        # _dispatch_group) are micro-fenced so per-program device time
        # is measured even under depth-k overlap.  Disarmed: one read
        ds = udevstats.devstats()
        if ds is not None and ds.begin_cycle():
            # pre-drain queued-ahead device work UNTIMED: at depth > 2
            # older in-flight cycles are still executing, and the device
            # runs programs in order — without this the fence would
            # charge their remaining seconds to THIS cycle's programs.
            # Completion is observed by READBACK (np.asarray), not
            # block_until_ready — the axon tunnel does not block the
            # latter; packed is tiny, and re-reading it later is safe
            for res_old in self._pipeline.inflight_results():
                try:
                    np.asarray(res_old.packed)
                except Exception:
                    pass   # its own readback path recovers the fault
        # capture the event sequence BEFORE snapshotting: a chain is only
        # reusable if no event has landed since the state it embeds
        with self._chain_lock:
            chain_seq0 = self._chain_seq
        # ---- snapshot (reference: generic_scheduler.go:155 snapshot())
        self.cache.update_snapshot(self.snapshot)
        node_infos = self.snapshot.node_info_list
        n_nodes = len(node_infos)
        trace.step("Snapshotting scheduler cache and node infos done")
        if self.metrics:
            self.metrics.cache_size.set(n_nodes, "nodes")
            self.metrics.cache_size.set(self.cache.pod_count(), "pods")
            self.metrics.cache_size.set(len(self.cache.assumed_pods),
                                        "assumed_pods")

        # ---- host PreFilter + basic checks; build scheduleable set
        states: Dict[str, CycleState] = {}
        live: List[QueuedPodInfo] = []
        outcomes: List[ScheduleOutcome] = []
        for qp in qpods:
            state = CycleState()
            st = fwk.run_pre_filter_plugins(state, qp.pod)
            if not st.is_success():
                outcomes.append(self._fail(fwk, qp, state, "",
                                           st.message() or "prefilter failed",
                                           preemption_may_help=not st.code
                                           == Code.UNSCHEDULABLE_AND_UNRESOLVABLE))
                self._record_decision(qp.pod, "unschedulable",
                                      message=st.message()
                                      or "prefilter failed",
                                      blocking=["PreFilter"])
                continue
            states[qp.pod.uid] = state
            live.append(qp)
        if not live:
            trace.finish()
            return None, outcomes
        if n_nodes == 0:
            for qp in live:
                outcomes.append(self._fail(fwk, qp, states[qp.pod.uid], "",
                                           "0/0 nodes are available",
                                           preemption_may_help=False))
                self._record_decision(qp.pod, "unschedulable",
                                      message="0/0 nodes are available")
            trace.finish()
            return None, outcomes

        # ---- tensorize, or reuse the CHAINED cluster: the previous gang
        # cycle's materialized tensors already ARE this snapshot (no
        # unaccounted event landed), so skip the full rebuild entirely
        pinfos = [PodInfo(qp.pod) for qp in live]
        # nominated pods join the tensor world too (labels/terms for the
        # addNominatedPods topology overlay) — their vocab must be interned
        # before snapshot arrays are sized
        nom_pinfos = [PodInfo(pod) for pod, _ in self.queue.all_nominated()]
        journal_input = None
        with self._chain_lock:
            chain = self._chain
        use_chain = (chain is not None and chain["seq"] == chain_seq0
                     and self._chain_enabled(fwk)
                     and chain["profile"] == fwk.profile_name
                     and chain["n_nodes"] == n_nodes)
        if use_chain:
            builder = chain["builder"]
            builder.intern_pending(pinfos + nom_pinfos)
            if _vocab_caps(builder.table) != chain["caps"]:
                use_chain = False   # vocab bucket overflow: rebuild
        if use_chain:
            cluster = chain["cluster"]
            chain_pod_uids = chain["pod_uids"]
            if ujournal.journal() is not None:
                # journal provenance: this cycle's cluster is the
                # previous committed cycle's auction, materialized at
                # the pad buckets the chain recorded
                journal_input = ("chain", chain.get("pads"))
        else:
            # incremental tensorization (state/delta.py): the resident
            # device cluster is brought up to date by a bounded scatter
            # over the cycle's dirty rows; a full build() runs only on the
            # DeltaTensorizer's blessed resync path.  The chain branch
            # above is the zero-delta special case of the same pipeline.
            delta = self._delta.get(fwk.profile_name)
            if delta is None:
                delta = DeltaTensorizer(
                    hard_pod_affinity_weight=fwk.hard_pod_affinity_weight,
                    mesh=self._mesh, profile=fwk.profile_name)
                self._delta[fwk.profile_name] = delta
            # in-place buffer donation is only safe when NO
            # dispatched-but-uncommitted pipelined cycle still reads the
            # resident buffers (its commit-side preemption wave and
            # decision audit dispatch against prep.cluster).  ONE source
            # of truth per call: the depth-k drain passes its in-flight
            # ring explicitly; callers that don't (the synchronous path,
            # scatter-recovery re-prepares) fall back to the executor's
            # ring so a prepare racing parked cycles can never donate
            # either.
            inflight = (uncommitted if uncommitted is not None
                        else self._pipeline.inflight_preps())
            # the donation-withholding set: every dispatched-but-
            # uncommitted ring cycle PLUS every prepared cycle whose
            # double-buffered batch upload is still in flight (its
            # dispatch hasn't consumed the resident yet) — one gate,
            # fed from both sources
            donate = delta.safe_to_donate(
                [p.cluster for p in inflight if p is not None]
                + [p.cluster for p in self._undispatched])
            # pending/nominated pods intern inside refresh (a compacting
            # resync re-interns them into its fresh table)
            cluster, dstats = delta.refresh(
                node_infos, pending=pinfos + nom_pinfos, donate=donate)
            # AFTER refresh: a compacting resync swaps the builder
            builder = delta.builder
            rec = trace.rec
            if rec is not None:
                for name, st0, st1 in dstats.spans:
                    rec.record_span(name, st0, st1,
                                    parent_id=trace.span_id,
                                    delta_rows=dstats.delta_rows)
                rec.meta["delta_rows"] = dstats.delta_rows
                rec.meta["resync"] = dstats.resync
                if dstats.resync:
                    rec.event("resync", parent_id=trace.span_id,
                              reason=dstats.reason)
            if dstats.resync:
                self.resync_count += 1
                if dstats.reason == "verify-divergence":
                    # the anti-entropy verifier caught device residents
                    # diverging from the host mirror and forced the
                    # targeted full resync — a recovery, not churn
                    self.recovery_log.append(
                        {"kind": "verify-resync",
                         "reason": dstats.reason,
                         "cycle": self.cycle_count})
                    if self.metrics is not None:
                        self.metrics.recoveries.inc("verify-resync")
            elif dstats.delta_rows > 0:
                # zero-dirty cycles (retry churn with no cache events) ran
                # no scatter — counting them would drag the row p50 to 0
                # and diverge from the span-based traceview digest
                self.delta_rows.append(dstats.delta_rows)
                self.delta_cycle_count += 1
            chain_pod_uids = delta.pod_uid_list()
            # journal capture seam (state/delta.py): the exact resync
            # snapshot / delta tables / zero-dirty marker this refresh
            # applied — None when the journal is disarmed
            journal_input = delta.take_capture()
            if journal_input is not None:
                if (fwk.profile_name in self._journal_force_anchor
                        and journal_input[0] != "resync"):
                    # THIS profile's discarded cycle applied a
                    # delta/resync capture that never journaled, so its
                    # resident is ahead of the journal stream —
                    # re-anchor from the mirror (bit-equal to the
                    # resident after any successful refresh, the
                    # anti-entropy verifier's invariant).  The capture
                    # format is owned by ONE site: the tensorizer's own
                    # resync seam
                    delta._capture_resync()
                    journal_input = delta.take_capture()
                self._journal_force_anchor.discard(fwk.profile_name)
            with self._chain_lock:
                self._chain = None
            self._drop_chain_residency()
        spread_sels = [self.store.default_spread_selector(pi.pod)
                       for pi in pinfos]
        pb = PodBatchBuilder(builder.table)
        batch = self._jax.tree.map(np.asarray,
                                   pb.build(pinfos, spread_selectors=spread_sels))
        batch_dev = None
        if self._mesh is not None:
            # DOUBLE-BUFFERED transfer: start the sharded upload of this
            # wave's batch NOW — in the depth-k drain, prepare(k+1) runs
            # while wave k's auction occupies the device, so the
            # host->device transfer rides behind the running program
            # (FIFO tunnel) instead of serializing in front of k+1's
            # dispatch.  device_put is async; the span below measures
            # issue time, and traceview shows it inside the prepare
            # stage — i.e. UNDER the previous wave's device window
            from .parallel import mesh as pmesh
            t_up = utrace.wallclock()
            batch_dev = pmesh.shard_batch(batch, self._mesh)
            if trace.rec is not None:
                nbytes = sum(np.asarray(x).nbytes
                             for x in self._jax.tree.leaves(batch))
                trace.rec.record_span("batch-upload", t_up,
                                      utrace.wallclock(),
                                      parent_id=trace.span_id,
                                      bytes=int(nbytes),
                                      double_buffered=True)
        B = batch.valid.shape[0]
        N = cluster.allocatable.shape[0]
        if trace.rec is not None:
            # the pod-axis bucket this cycle dispatches in — the unit
            # tools/kubeaot --prune works in (buckets the recorder never
            # saw are dead ladder rungs, dropped from the artifact set)
            trace.rec.meta["pod_bucket"] = int(cluster.pod_valid.shape[0])

        # ---- host filter plugins -> mask fed into the device program.
        # ONE walk of the host plugins' relevance predicates per pod per
        # CYCLE (_host_relevance) computes BOTH "any relevant" (the
        # commit-time re-check gate) and "any relevant beyond the
        # device-covered volume family" (the per-node Python loop gate).
        # The pipelined drain walks it up front for its serialize
        # decision and passes the map in, so the walk never runs twice.
        from .state.volumes import (DEVICE_COVERED_PLUGINS,
                                    build_volume_overlay, volume_mask)
        if relevance is None:
            relevance = self._host_relevance(fwk, live)
        host_relevant: Dict[str, bool] = {}
        host_uncovered: Dict[str, bool] = {}
        for qp in live:
            rel, unc = relevance[qp.pod.uid]
            host_relevant[qp.pod.uid] = rel
            host_uncovered[qp.pod.uid] = unc
        # the volume family evaluates ON DEVICE (state/volumes.py): one
        # jitted [B, N] mask replaces ~B x N Python filter calls for
        # PVC-heavy batches.  The host plugins still run at commit time
        # (host_relevant above), preserving intra-batch race checks.
        enabled_hosts = {p.name() for p in fwk.host_filter_plugins}
        vol_mask_dev = None
        if (DEVICE_COVERED_PLUGINS & enabled_hosts
                and any(qp.pod.spec.volumes for qp in live)):
            overlay = build_volume_overlay(
                self.store, node_infos, [qp.pod for qp in live],
                builder.table, enabled_hosts)
            if overlay is not None:
                vol_mask_dev = volume_mask(cluster, overlay)
        host_ok = np.ones((B, N), bool)
        any_host = False
        host_reject: Dict[str, Dict[str, int]] = {}
        audit = self.decisions.enabled
        for i, qp in enumerate(live):
            if not host_relevant[qp.pod.uid]:
                continue
            if vol_mask_dev is not None and not host_uncovered[qp.pod.uid]:
                continue   # every relevant host filter is device-covered
            any_host = True
            state = states[qp.pod.uid]
            for j, ni in enumerate(node_infos):
                st = fwk.run_filter_plugins(state, qp.pod, ni)
                host_ok[i, j] = st.is_success()
                if audit and not st.is_success():
                    # per-reason node counts for the decision audit
                    # ("4 nodes rejected by host filter: too many volumes")
                    counts = host_reject.setdefault(qp.pod.uid, {})
                    for r in (st.reasons or ["host filter failed"]):
                        counts[r] = counts.get(r, 0) + 1
        # ---- nominated-pods two-pass overlay (addNominatedPods,
        # generic_scheduler.go:530,594-612): equal/higher-priority pods
        # nominated by preemption reserve their nominated nodes' capacity
        # AND contribute topology terms (anti-affinity/spread).  The mask
        # stays a DEVICE array — pulling a [B, N] bool through the tunnel
        # would cost more than the whole device program
        batch_topo_keys = self._batch_topo_keys(builder.table, pinfos)
        nom_mask = self._nominated_overlay_mask(fwk, builder, cluster,
                                                batch, live, node_infos,
                                                batch_topo_keys)
        # host Score/NormalizeScore plugins -> a [B, N] score bias the
        # device program adds before selectHost (framework.go:579-656).
        # Normalization runs over ALL valid nodes pre-dispatch (the
        # reference normalizes over the filtered set — a documented
        # deviation that keeps the single-readback design)
        score_bias = None
        if fwk.host_score_plugins:
            node_names = [ni.node_name for ni in node_infos]
            nodes_raw = [ni.node for ni in node_infos]
            bias = np.zeros((B, N), np.float32)
            any_bias = False
            for i, qp in enumerate(live):
                if not any(fwk._relevant(p, qp.pod)
                           for p in fwk.host_score_plugins):
                    continue
                state = states[qp.pod.uid]
                st = fwk.run_pre_score_plugins(state, qp.pod, nodes_raw)
                if not st.is_success():
                    # the reference fails the pod's cycle here; we keep
                    # the pod but drop its host scores (documented
                    # deviation — a failing PreScore must not abort the
                    # whole batch)
                    import logging
                    logging.getLogger("kubetpu").warning(
                        "prescore failed for %s: %s; host scores dropped",
                        qp.pod.metadata.name, st.message())
                    continue
                try:
                    plugin_scores = fwk.run_host_score_plugins(
                        state, qp.pod, node_names)
                except RuntimeError as e:
                    import logging
                    logging.getLogger("kubetpu").warning(
                        "host score failed for %s: %s; scores dropped",
                        qp.pod.metadata.name, e)
                    continue
                for vals in plugin_scores.values():
                    bias[i, :len(vals)] += vals
                    any_bias = True
            if any_bias:
                score_bias = self._jax.numpy.asarray(bias)
        host_ok_dev = None
        if any_host:
            host_ok_dev = self._jax.numpy.asarray(host_ok)
        if vol_mask_dev is not None:
            host_ok_dev = (vol_mask_dev if host_ok_dev is None
                           else host_ok_dev & vol_mask_dev)
        if nom_mask is not None:
            host_ok_dev = (nom_mask if host_ok_dev is None
                           else host_ok_dev & nom_mask)
        cfg = programs.ProgramConfig(
            filters=fwk.tensor_filters, scores=fwk.tensor_scores,
            hostname_topokey=max(builder.table.topokey.get(api.LABEL_HOSTNAME), 0),
            plugin_args=fwk.tensor_plugin_args(builder.table),
            # 0 => the reference's adaptive default (types.go:251); only
            # the sequential replay consumes it — gang needs the global view
            percentage_of_nodes_to_score=(
                self.config.percentage_of_nodes_to_score
                if self.config.percentage_of_nodes_to_score > 0 else 0),
            # restrict the same-pair matmuls to the keys THIS batch's terms
            # actually use (superset contract, see ProgramConfig)
            active_topo_keys=batch_topo_keys)
        from .preemption import CycleContext
        cycle_ctx = CycleContext(
            builder=builder, cluster=cluster, cfg=cfg,
            node_infos=node_infos, batch=batch,
            row_of={qp.pod.uid: i for i, qp in enumerate(live)})
        # existing-pod tensor rows by uid (chained clusters' row order
        # diverges from node_infos build order; preemption victim masking
        # needs the true mapping)
        cycle_ctx.pod_rows = {uid: i for i, uid in enumerate(chain_pod_uids)
                              if uid}
        trace.step("Tensorizing snapshot and pod batch done")

        from .framework.types import pod_with_affinity
        # per-round topology re-evaluation only pays off when some pod
        # actually carries topology terms; a term-free batch takes the
        # cheaper static path (round-0 verdicts are provably invariant)
        needs_topo = (any(pod_with_affinity(qp.pod)
                          or qp.pod.spec.topology_spread_constraints
                          for qp in live)
                      # service/RC replicas score via
                      # DefaultPodTopologySpread even without explicit
                      # terms — they need intra-batch placements too
                      or any(s is not None for s in spread_sels))
        prep = PreparedCycle(
            fwk=fwk, trace=trace, chain_seq0=chain_seq0,
            node_infos=node_infos, states=states, live=live, pinfos=pinfos,
            builder=builder, cluster=cluster, batch=batch,
            host_relevant=host_relevant, host_ok_dev=host_ok_dev, cfg=cfg,
            cycle_ctx=cycle_ctx, needs_topo=needs_topo,
            used_chain=use_chain, chain_pod_uids=chain_pod_uids,
            score_bias=score_bias, host_reject=host_reject,
            relevance=relevance, journal_input=journal_input,
            batch_dev=batch_dev)
        if batch_dev is not None:
            # until _dispatch_group consumes the upload, this cycle's
            # dispatch still reads the resident cluster — withhold
            # donation (see __init__._undispatched)
            self._undispatched.append(prep)
        return prep, outcomes

    def _dispatch_group(self, prep: PreparedCycle, extra_uncommitted: int = 0):
        """Device dispatch of a prepared cycle (async through the tunnel),
        plus the speculative chain materialize so the NEXT cycle can
        tensorize against this cycle's placements before they commit.
        extra_uncommitted: pods dispatched in earlier cycles whose commits
        (and so cache.pod_count()) have not landed yet — the pipelined
        drain passes the in-flight cycle's batch size so the chain bucket
        guard sees the same fresh-rebuild estimate the synchronous path
        would."""
        fwk, cluster, batch, cfg = (prep.fwk, prep.cluster, prep.batch,
                                    prep.cfg)
        host_ok_dev, cycle_ctx = prep.host_ok_dev, prep.cycle_ctx
        n_nodes = len(prep.node_infos)
        # the double-buffered upload is consumed by THIS dispatch; the
        # cycle graduates to the ordinary in-flight donation set
        # (identity filter: PreparedCycle holds arrays, == is undefined)
        self._undispatched = [p for p in self._undispatched
                              if p is not prep]
        if prep.batch_dev is not None:
            # consume the pre-uploaded sharded batch (shard_batch passes
            # committed-sharding arrays through untouched)
            batch = prep.batch_dev
        # deadline-guard anchor + chaos seam (utils/chaos.py "dispatch"):
        # an injected error models the device dying under the program; an
        # injected stall models a hung tunnel — both recovered by
        # _recover_cycle via the guarded call sites / readback.
        # wallclock (utils/trace.py): the deadline and the SLO dispatch
        # stage are durations-by-subtraction — an NTP step must not
        # corrupt them
        prep.dispatch_t0 = utrace.wallclock()
        if self._dispatch_deadline > 0:
            # idempotent singleton; first call installs the
            # jax.monitoring listener, later calls are a lock + read
            from .utils.sanitize import install_compile_timer
            prep.compile_snap = install_compile_timer().snapshot()
        uchaos.raise_or_stall("dispatch")
        seq_start = 0
        # ---- device: one program for the whole group (scan or auction)
        if self.config.mode == "gang":
            needs_topo = prep.needs_topo
            if self._mesh is not None:
                from .parallel import mesh as pmesh
                res = pmesh.sharded_schedule_gang(
                    cluster, batch, cfg, self._next_rng(), self._mesh,
                    host_ok=host_ok_dev,
                    intra_batch_topology=needs_topo,
                    score_bias=prep.score_bias)
            else:
                from .models.gang import run_auction
                res = run_auction(
                    cluster, batch, cfg, self._next_rng(),
                    host_ok=host_ok_dev,
                    intra_batch_topology=needs_topo,
                    score_bias=prep.score_bias,
                    kernel_backend=self.config.kernel_backend)
            # the auction already produced per-pod verdict rows; share them
            # lazily so preemption can skip its candidates pass without the
            # scheduler paying a multi-MB transfer it may never need
            cycle_ctx.set_lazy_verdicts(res.feasible0, res.unresolvable)
        else:
            start = seq_start = self._next_start_node_index % max(n_nodes, 1)
            if self._mesh is not None:
                from .parallel import mesh as pmesh
                res = pmesh.sharded_schedule_sequential(
                    cluster, batch, cfg, self._next_rng(), self._mesh,
                    hard_pod_affinity_weight=float(
                        fwk.hard_pod_affinity_weight),
                    host_ok=host_ok_dev,
                    start_index=start,
                    score_bias=prep.score_bias)
            else:
                res = schedule_sequential(
                    cluster, batch, cfg, self._next_rng(),
                    hard_pod_affinity_weight=float(
                        fwk.hard_pod_affinity_weight),
                    host_ok=host_ok_dev,
                    start_index=start,
                    score_bias=prep.score_bias)
        # devstats deep-timing micro-fence (utils/devstats.py): on the
        # sampled cycles, block until the dispatched program completes
        # and record the wall seconds as MEASURED per-program device
        # time — the only number that stays honest under depth-k
        # overlap, where device_wait_s (the readback block) reads near
        # zero.  The fence serializes work the pipeline would have
        # hidden, so it runs on 1/N cycles and its cumulative cost is
        # recorded (fence_wait_s).  Disarmed: one attribute read.
        ds = udevstats.devstats()
        if ds is not None and ds.deep_active():
            program = ("run_auction" if self.config.mode == "gang"
                       else "schedule_sequential")
            with utrace.flight_span("device-fence", program=program) as sp:
                # fence = a readback of the tiny packed vector, NOT
                # block_until_ready: the axon tunnel does not block the
                # latter (it would measure dispatch only); the readback
                # is the one real completion signal on every backend.
                # Its fixed tunnel latency is part of the recorded fence
                # overhead, and re-reading packed in _readback_group is
                # safe (transfers are non-destructive)
                t_f = time.perf_counter()
                np.asarray(res.packed)
                dt_f = time.perf_counter() - t_f
                if sp is not None:
                    sp.args["device_time_s"] = round(dt_f, 6)
            prep.devstats_fenced = True
            prep.devstats_fence_s = dt_f
            ds.record_program(
                program, dt_f, source="fence",
                in_bytes=udevstats.pytree_nbytes((cluster, batch)))
        if ujournal.journal() is not None:
            # journal provenance: the RNG fold counter this dispatch
            # consumed (_next_rng bumped it inside the call above) and
            # the sequential rotating start — exactly what kubereplay
            # feeds back into the same program
            prep.journal_rng = self._rng_counter
            prep.journal_start = seq_start
        # request the packed readback transfer BEFORE enqueueing the chain
        # materialize: the tunnel serves FIFO, so a transfer requested
        # after materialize would wait for it — this way the readback
        # completes right after the auction and the materialize overlaps
        # the host's commit loop
        try:
            res.packed.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass
        # ---- speculative chain (gang only): materialize this cycle's
        # placements into the next cycle's cluster NOW, on device, so the
        # pipelined drain can tensorize+dispatch cycle k+1 while this
        # cycle's commit loop runs.  _finish_group discards it if a commit
        # fails (the device-side placements then diverged from reality).
        chain_ok = self.config.mode == "gang" and self._chain_enabled(fwk)
        if chain_ok:
            from .utils.intern import pow2_bucket
            B_cap = batch.valid.shape[0]
            p_next = int(cluster.pod_valid.shape[0]) + B_cap
            # never chain into a BIGGER pod-axis bucket than a fresh
            # rebuild would use: pow2 slack compounds across cycles
            # (bucket + B -> next bucket) and a rebuild compacts it —
            # chaining past this line doubles HBM for nothing.  (Estimated
            # pre-commit: cache.pod_count() excludes this cycle's assumes,
            # so allow one batch of slack plus any in-flight cycle's.)
            fresh_p = pow2_bucket(self.cache.pod_count() + extra_uncommitted
                                  + 2 * B_cap)
            if pow2_bucket(p_next) > fresh_p:
                chain_ok = False
        if chain_ok:
            from .models.gang import materialize_assigned
            ta = batch.raa.valid.shape[1]
            e_next = int(cluster.filter_terms.valid.shape[0]) + B_cap * ta
            next_cluster = materialize_assigned(
                cluster, batch, res.chosen,
                res.requested, res.nz, res.ports_used,
                pad_pods_to=pow2_bucket(p_next),
                pad_terms_to=pow2_bucket(e_next),
                extend_score_terms=True,
                hard_pod_affinity_weight=float(
                    fwk.hard_pod_affinity_weight))
            uids = list(prep.chain_pod_uids)
            uids.extend(pi.pod.uid for pi in prep.pinfos)
            uids.extend([None] * (B_cap - len(prep.pinfos)))  # batch padding
            uids.extend([None] * (pow2_bucket(p_next) - len(uids)))
            with self._chain_lock:
                self._chain = dict(builder=prep.builder,
                                   cluster=next_cluster,
                                   pod_uids=uids, seq=prep.chain_seq0,
                                   caps=_vocab_caps(prep.builder.table),
                                   profile=fwk.profile_name,
                                   n_nodes=n_nodes,
                                   # journal provenance: the pad buckets
                                   # a chained successor must feed back
                                   # into materialize_assigned to rebuild
                                   # this cluster bit-exactly
                                   pads=(pow2_bucket(p_next),
                                         pow2_bucket(e_next)))
            # residency-ledger seam (utils/devstats.py): the speculative
            # chain is a SECOND full cluster resident until the next
            # cycle consumes it — the capacity planner must count it.
            # Memoized on (profile, pads, n_nodes): identical shapes
            # register identical bytes, so the per-table walk runs only
            # when the pad buckets actually move
            ds = udevstats.devstats()
            if ds is not None:
                lkey = (fwk.profile_name, pow2_bucket(p_next),
                        pow2_bucket(e_next), n_nodes)
                # the has_group check backstops a bind-thread discard
                # racing this registration (the memo alone could read
                # fresh while the entry was just dropped)
                if self._chain_ledger_key != lkey \
                        or not ds.has_group("chain"):
                    udevstats.register_cluster(
                        "chain", fwk.profile_name, next_cluster, n_nodes,
                        meta={"pads": [pow2_bucket(p_next),
                                       pow2_bucket(e_next)]})
                    self._chain_ledger_key = lkey
        elif self.config.mode == "gang":
            with self._chain_lock:
                self._chain = None
            self._drop_chain_residency()
        return res

    # ----------------------------------------------------------- recovery

    def _recover_cycle(self, prep: PreparedCycle, reason: str,
                       kind: str) -> List[ScheduleOutcome]:
        """Self-healing path for a cycle whose device dispatch errored or
        blew its deadline (kind: "dispatch-error" / "dispatch-deadline").
        Three moves, in order:

        1. DEMOTE the backend one rung with the reason recorded: a
           pallas-backed profile drops to the lax oracle path
           (utils/pallas_backend.demote — process-wide, every later
           cycle routes lax), and an armed AOT runtime disarms
           (AOT -> trace; the persistent-cache/trace ladder still
           serves).  The demotion is an incident INSTANT on the cycle's
           flight record, visible in /debug/flightz and traceview.
        2. INVALIDATE the device residents this dispatch may have
           poisoned: the speculative chain and the profile's
           DeltaTensorizer cluster — the next cycle resyncs from a fresh
           host walk (the blessed "initial" path).
        3. REQUEUE the cycle's pods through the backoff queue.  Recovery
           runs strictly BEFORE the commit loop, so nothing was
           reserved, assumed or bound: pods are never lost and never
           double-bound — they simply retry against the demoted backend.

        Never raises: the serving loop must survive any fault this
        handles."""
        import logging
        logging.getLogger("kubetpu").warning(
            "cycle recovery (%s): %s; %d pods requeued", kind, reason,
            len(prep.live))
        # demote ONE rung per fault, outermost first (the ladder the
        # docstring and README describe): a pallas-backed profile drops
        # to lax; only a fault that recurs on the lax path disarms AOT.
        # Demoting everything at once would throw away both fast paths —
        # and the evidence of which layer actually faulted — on the
        # first blip.
        demoted = []
        if self.config.kernel_backend == "pallas":
            from .utils import pallas_backend as PB
            if PB.demotion() is None:
                PB.demote("%s: %s" % (kind, reason[:200]))
                demoted.append("pallas->lax")
        if not demoted:
            from .utils import aot as _aot
            if _aot.active_runtime() is not None:
                _aot.disarm(reason="%s: %s" % (kind, reason[:200]))
                demoted.append("aot->trace")
        with self._chain_lock:
            self._chain = None
            self._chain_seq += 1
        self._drop_chain_residency()
        self._delta.pop(prep.fwk.profile_name, None)
        for qp in prep.live:
            try:
                self.queue.add_unschedulable_if_not_present(
                    qp, qp.scheduling_cycle)
            except ValueError:
                pass
        # unschedulable -> backoff/active now (per-pod backoff paces the
        # retry); without the move the pods would wait for the periodic
        # leftover flush
        self.queue.move_all_to_active_or_backoff_queue("DispatchRecovery")
        self._deadline_grace = 2
        self.recovery_log.append(
            {"kind": kind, "reason": reason, "pods": len(prep.live),
             "demoted": demoted, "cycle": self.cycle_count})
        if self.metrics is not None:
            self.metrics.recoveries.inc(kind)
        if prep.trace.rec is not None:
            prep.trace.rec.event(
                "backend-demotion" if demoted else "dispatch-recovery",
                kind=kind, reason=reason[:256],
                demoted=",".join(demoted))
        err = f"dispatch recovered ({kind}): pod requeued"
        return [ScheduleOutcome(pod=qp.pod, node="", err=err)
                for qp in prep.live]

    def _readback_guarded(self, prep: PreparedCycle, res):
        """(packed, None) on success; (None, recovery outcomes) when the
        readback raised — async dispatch errors surface HERE, at the
        cycle's only device sync — or when dispatch-to-readback wall
        time exceeded the configured deadline.  Either way the cycle is
        discarded pre-commit and recovered (_recover_cycle)."""
        if prep.parked_t:
            # time parked in the in-flight ring = caller think time
            # between schedule_pending calls — exempt from the deadline
            prep.host_exempt_s += utrace.wallclock() - prep.parked_t
            prep.parked_t = 0.0
        try:
            packed = self._readback_group(prep, res)
        except Exception as e:
            out = self._recover_cycle(prep, repr(e), "dispatch-error")
            prep.trace.finish(recovered="dispatch-error")
            return None, out
        dl = self._dispatch_deadline
        if dl > 0 and prep.dispatch_t0:
            if self._deadline_grace > 0:
                self._deadline_grace -= 1
            else:
                elapsed = (utrace.wallclock() - prep.dispatch_t0
                           - prep.host_exempt_s)
                compiled = False
                if prep.compile_snap is not None:
                    # a cycle that paid ANY XLA compile or cache load is
                    # exempt wholesale: the deadline gates steady-state
                    # DEVICE health, and demoting a backend over a
                    # legitimate first-compile would latch the whole
                    # process off its fast paths.  (Tracing/lowering
                    # time has no jax.monitoring event, so subtracting
                    # measured seconds under-exempts — the any-activity
                    # check is the robust form.  A device hang on a
                    # compile cycle is caught one cycle later.)
                    from .utils.sanitize import install_compile_timer
                    d = install_compile_timer().snapshot()
                    compiled = any(d[k] != prep.compile_snap[k]
                                   for k in d)
                if not compiled and elapsed > dl:
                    out = self._recover_cycle(
                        prep, "dispatch+readback %.3fs > deadline %.3fs"
                        % (elapsed, dl), "dispatch-deadline")
                    prep.trace.finish(recovered="dispatch-deadline")
                    return None, out
        return packed, None

    def _finish_group(self, prep: PreparedCycle, res) -> List[ScheduleOutcome]:
        """Readback + commit half of a cycle.  The packed readback is the
        cycle's ONLY device->host sync point."""
        packed, recovered = self._readback_guarded(prep, res)
        if packed is None:
            # the cycle never happened as far as state goes: its pods are
            # requeued and its residents invalidated; a later pipelined
            # cycle dispatched against its chain must also re-run
            self._last_commit_failed = True
            self._sync_flight_dropped()
            return recovered
        with prep.trace.stage("commit"):
            out = self._commit_group(prep, packed)
        if self.config.mode == "gang":
            # per-cycle auction rounds as cycle meta: bench aggregates the
            # histogram across cycles and traceview shows a digest column,
            # so the round-count reduction ROADMAP item 3 claims is
            # directly observable per run, not just as a max
            prep.trace.finish(auction_rounds=self.last_gang_rounds,
                              kernel_backend=self._gang_backend(prep))
        else:
            prep.trace.finish()
        self._sync_flight_dropped()
        return out

    def _gang_backend(self, prep: PreparedCycle) -> str:
        """The kernel backend this cycle actually traced (pallas falls
        back per cycle on unsupported routing, e.g. topology batches)."""
        if self._mesh is not None or self.config.kernel_backend != "pallas":
            return "lax"
        from .utils import pallas_backend as PB
        return PB.effective_backend(prep.cfg, prep.needs_topo, "pallas",
                                    batch=prep.batch)

    def _readback_group(self, prep: PreparedCycle, res) -> np.ndarray:
        """ONE device->host readback per cycle: the packed [3B+1] i32 view
        (chosen | n_feasible | all_unresolvable | rounds / next_start).
        The tunnel pays ~100 ms latency per transfer AND serves transfers
        FIFO behind queued programs, so the pipelined drain must issue this
        BEFORE dispatching the next cycle; everything the host needs rides
        one small array — the big tensors (requested, masks) stay on
        device for chaining / lazy preemption verdicts."""
        with prep.trace.stage("packed-readback") as sp:
            t_dev = utrace.wallclock()
            packed = np.asarray(res.packed)
            t_done = utrace.wallclock()
            wait = t_done - t_dev
            prep.readback_done_t = t_done
            prep.device_wait = wait
            if sp is not None:
                # per-span device-wait attribution: the readback is the
                # cycle's only observable device sync
                sp.args["device_wait_s"] = round(wait, 6)
        self.device_wait_s += wait
        return packed

    def _commit_group(self, prep: PreparedCycle,
                      packed: np.ndarray) -> List[ScheduleOutcome]:
        fwk, trace = prep.fwk, prep.trace
        live, states, pinfos = prep.live, prep.states, prep.pinfos
        node_infos, cycle_ctx = prep.node_infos, prep.cycle_ctx
        n_nodes = len(node_infos)
        B = prep.batch.valid.shape[0]
        self.cycle_count += 1
        outcomes: List[ScheduleOutcome] = []
        if self.config.mode != "gang":
            self._next_start_node_index = int(packed[3 * B])
        else:
            # auction round count (diagnostics; bench reports it)
            self.last_gang_rounds = int(packed[3 * B])
            from .utils.flops import gang_cycle_flops
            cyc_flops = gang_cycle_flops(
                prep.cluster, prep.batch, prep.cfg, self.last_gang_rounds,
                intra_batch_topology=prep.needs_topo,
                kernel_backend=self._gang_backend(prep))
            self.device_flops += cyc_flops
            if prep.devstats_fenced:
                # pair the cycle's analytic FLOP count with ITS OWN
                # fence's measured seconds (the round count — and so the
                # FLOPs — is only known after the readback, and newer
                # fence samples may have landed since)
                ds = udevstats.devstats()
                if ds is not None:
                    ds.attribute_flops("run_auction", cyc_flops,
                                       seconds=prep.devstats_fence_s)
        # one .tolist() per field: the commit loop below reads every entry,
        # and plain Python ints beat a numpy scalar box per access at 4k
        # pods/cycle (kubelint host-sync audit)
        chosen = packed[:B][:len(live)].tolist()
        n_feas = packed[B:2 * B][:len(live)].tolist()
        unres = (packed[2 * B:3 * B][:len(live)] != 0).tolist()
        trace.step("Computing predicates and priorities on device done")

        # ---- commit each placement in scan order; failures DEFER until
        # every commit has landed so all preemption attempts share one
        # verdict refresh against the final committed state (N failed pods
        # cost one [B, N] pass, not N)
        deferred = []  # (outcome index, qp, state, message, may_help)
        commit_failed = False
        audit = self.decisions.enabled
        flight = trace.rec
        # per-pod latency SLO (utils/slo.py): one tracker read per cycle;
        # disarmed, no stage vectors are built and no clock is read — the
        # zero-new-locks hot-path contract (tests/test_slo.py)
        slo_trk = uslo.tracker()
        # durable cycle journal (utils/journal.py): reserve this cycle's
        # record id UP FRONT so the SLO exemplars of its pods can carry
        # it (the record itself appends after the commit loop, once the
        # outputs and audit summary exist).  Disarmed: one attribute read
        jr = ujournal.journal()
        jr_seq = jr.next_seq() if jr is not None else 0
        slo_host_dispatch = 0.0
        if slo_trk is not None and prep.dispatch_t0:
            # host share of the dispatch->readback window (program
            # enqueue); the device share is prep.device_wait.  The
            # window's HOST-EXEMPT share — other ring slots' commit
            # loops and readbacks, pipelined parking — is subtracted so
            # depth-k overlap doesn't double-count the same wall-clock
            # seconds into every in-flight cycle's pods (per-slot stage
            # attribution, utils/slo.py)
            slo_host_dispatch = max(prep.readback_done_t - prep.dispatch_t0
                                    - prep.device_wait
                                    - prep.host_exempt_s, 0.0)
        for i, qp in enumerate(live):
            state = states[qp.pod.uid]
            if chosen[i] < 0:
                outcomes.append(None)
                deferred.append((len(outcomes) - 1, qp, state,
                                 f"0/{n_nodes} nodes are available",
                                 not unres[i]))
                continue
            node_name = node_infos[chosen[i]].node_name
            slo = (self._slo_prefix(qp, prep, slo_host_dispatch, flight,
                                    jr_seq)
                   if slo_trk is not None and qp.pop_timestamp else None)
            outcome = self._commit(fwk, qp, state, node_name,
                                   n_feas[i], pinfo=pinfos[i],
                                   host_relevant=prep.host_relevant[qp.pod.uid],
                                   flight=flight, slo=slo)
            if outcome.node:
                # preemption for pods failing later in this batch must see
                # this placement (CycleContext.cluster_now overlay)
                cycle_ctx.note_commit(i, chosen[i])
                if audit:
                    self._record_decision(qp.pod, "scheduled",
                                          node=outcome.node,
                                          n_feasible=n_feas[i])
            else:
                commit_failed = True
                if audit:
                    self._record_decision(qp.pod, "unschedulable",
                                          message=outcome.err or
                                          "commit failed",
                                          n_feasible=n_feas[i])
            outcomes.append(outcome)
        # ---- preemption WAVE: every preemption-eligible FitError of this
        # cycle is served by ONE batched what-if (preemption.preempt_wave)
        # instead of a per-pod candidates pass + what-if dispatch each.
        # The per-pod PostFilter below short-circuits on the recorded wave
        # verdicts; if the wave itself fails, it records nothing and the
        # per-pod path serves as the fallback.  Only safe when
        # DefaultPreemption is the first PostFilter plugin — an earlier
        # custom plugin could resolve the failure without evictions.
        wave_pods = [qp.pod for _, qp, _, _, mh in deferred if mh]
        if wave_pods and self.preemptor is not None:
            from .plugins.intree import DefaultPreemption
            pf = fwk.post_filter_plugins
            if pf and isinstance(pf[0], DefaultPreemption):
                try:
                    with trace.stage("preemption-wave",
                                     pods=len(wave_pods)):
                        self.preemptor.preempt_wave(fwk, cycle_ctx,
                                                    wave_pods)
                except Exception:
                    import logging
                    logging.getLogger("kubetpu").warning(
                        "preemption wave failed; per-pod fallback",
                        exc_info=True)
        # ---- decision audit: fold the per-(pod, node) filter verdicts
        # already computed on device into per-plugin attribution for the
        # failed pods (one extra packed readback, only on cycles that have
        # failures and only with the audit enabled)
        audit_rows = {}
        if deferred and audit:
            # retry-churn dedup: a persistent unschedulable tail fails
            # with the SAME pod set against the SAME state every cycle —
            # re-dispatching the audit would add a device sync per cycle
            # (and, pipelined, serialize behind the in-flight dispatch)
            # for identical answers.  Reuse holds only when nothing
            # placed, nothing evicted and no preemption wave ran this
            # cycle; any success or wave recomputes.
            uids = frozenset(qp.pod.uid for _, qp, _, _, _ in deferred)
            cached = self._audit_cache
            if (cached is not None and cached[0] == uids
                    and cycle_ctx.commits == 0 and not wave_pods):
                audit_rows = cached[1]
            else:
                with trace.stage("decision-audit", pods=len(deferred)):
                    audit_rows = self._audit_failures(
                        prep, [qp for _, qp, _, _, _ in deferred])
                self._audit_cache = (uids, audit_rows)
        # pod_verdicts refreshes the shared verdicts lazily on the FIRST
        # preemption attempt that needs them (and the min-priority gate may
        # skip them entirely), so no eager refresh here
        for idx, qp, state, msg, mh in deferred:
            outcomes[idx] = self._fail(fwk, qp, state, "", msg,
                                       preemption_may_help=mh,
                                       cycle=cycle_ctx)
            if audit:
                info = audit_rows.get(qp.pod.uid, {})
                self._record_decision(
                    qp.pod, "unschedulable", message=msg,
                    nominated_node=qp.pod.status.nominated_node_name or "",
                    host_reasons=prep.host_reject.get(qp.pod.uid),
                    **info)
            if (slo_trk is not None and not mh and qp.pop_timestamp
                    and not qp.slo_unres_observed):
                # terminally unresolvable this cycle (no plugin verdict
                # can change and preemption cannot help): record the
                # vector now — there is no bind stage to wait for.
                # Once per pod: the requeue path retries it every
                # cluster event, and re-recording each failing cycle
                # would multi-count the pod in the sketches
                qp.slo_unres_observed = True
                self._slo_observe_terminal(
                    slo_trk,
                    self._slo_prefix(qp, prep, slo_host_dispatch, flight,
                                     jr_seq),
                    qp, "unresolvable")
        # a commit-path failure invalidates the speculative chain (and any
        # later cycle already dispatched against it — the pipelined drain
        # reads _last_commit_failed and re-runs that cycle)
        self._last_commit_failed = commit_failed
        if commit_failed and self.config.mode == "gang":
            with self._chain_lock:
                self._chain = None
            self._drop_chain_residency()
        if jr is not None:
            # one self-contained replayable record per committed cycle;
            # ANY failure (unpicklable capture, disk, injected chaos)
            # degrades to a counted drop — recording never fails a cycle
            try:
                self._journal_append(jr, jr_seq, prep, packed, outcomes,
                                     audit_rows)
            except Exception:
                jr.note_drop()
                import logging
                logging.getLogger("kubetpu").warning(
                    "cycle journal record %d dropped", jr_seq,
                    exc_info=True)
        trace.step("Committing placements done")
        trace.log_if_long()
        return outcomes

    @staticmethod
    def _slo_prefix(qp: QueuedPodInfo, prep: PreparedCycle,
                    host_dispatch: float, flight,
                    journal_seq: int = 0) -> Dict[str, float]:
        """The cycle-side half of a pod's per-stage latency vector
        (utils/slo.py): queue_wait/backoff/cycle_wait/dispatch/device,
        plus underscore-prefixed meta keys the terminal observer pops
        before recording (the readback anchor for the commit stage, the
        flight-recorder cycle seq the exemplar links to, and the cycle's
        journal record id when KUBETPU_JOURNAL is armed).  Called only
        with the tracker armed and a stamped pop time."""
        return {
            "queue_wait": max(qp.pop_timestamp - qp.timestamp, 0.0),
            "backoff": max(qp.timestamp - qp.initial_attempt_timestamp,
                           0.0),
            "cycle_wait": max((prep.dispatch_t0 or qp.pop_timestamp)
                              - qp.pop_timestamp, 0.0),
            "dispatch": host_dispatch,
            "device": prep.device_wait,
            "_readback_done_t": prep.readback_done_t,
            "_flight_seq": float(flight.seq) if flight is not None else 0.0,
            "_journal_seq": float(journal_seq),
        }

    def _slo_observe_terminal(self, trk, prefix: Dict[str, float],
                              qp: QueuedPodInfo, outcome: str,
                              bind_start: Optional[float] = None) -> None:
        """Complete a pod's cycle-side stage vector (_slo_prefix) with
        the terminal stages — commit (readback -> bind start, or ->
        now for failures), bind (when one ran), e2e — and record it.
        The ONLY consumer of the prefix's underscore meta keys."""
        now = utrace.wallclock()
        stages = dict(prefix)
        seq = stages.pop("_flight_seq", 0)
        jseq = stages.pop("_journal_seq", 0)
        rb = stages.pop("_readback_done_t", 0.0)
        end = bind_start if bind_start is not None else now
        stages["commit"] = max(end - rb, 0.0)
        if bind_start is not None:
            stages["bind"] = max(now - bind_start, 0.0)
        stages["e2e"] = now - qp.initial_attempt_timestamp
        pod = qp.pod
        trk.observe_pod(stages, pod=pod.metadata.name,
                        namespace=pod.namespace, uid=pod.uid,
                        outcome=outcome, attempts=qp.attempts,
                        cycle=self.cycle_count, flight_seq=int(seq),
                        journal_seq=int(jseq))

    def _journal_note_discard(self, prep: PreparedCycle) -> None:
        """A prepared cycle is being discarded without committing (the
        pipelined executor's chain-break/scatter re-prepare).  If its
        journal capture carried resident state (delta scatter or resync),
        that state is now applied on device but will never be journaled
        — flag the PROFILE's next journaled cycle to re-anchor.
        Chain/noop captures carry no resident state and need nothing.
        Also drops the cycle from the double-buffer donation-withholding
        set — a discarded cycle's upload will never be consumed."""
        self._undispatched = [p for p in self._undispatched
                              if p is not prep]
        if prep.journal_input is not None \
                and prep.journal_input[0] in ("delta", "resync"):
            self._journal_force_anchor.add(prep.fwk.profile_name)

    def _journal_append(self, jr, jr_seq: int, prep: PreparedCycle,
                        packed: np.ndarray, outcomes, audit_rows) -> None:
        """Assemble + append one cycle-journal record (armed only; the
        caller degrades any failure to a counted drop).  The record is
        SELF-CONTAINED: everything tools/kubereplay needs to re-execute
        this cycle's device program and bit-match its packed output —
        inputs (cluster provenance, pod batch, cfg, masks, RNG fold),
        outputs (packed vector, placements, verdict summary) and the
        linkage ids into the flight-recorder seq and decision-audit
        cycle.  ``host_ok``/``score_bias`` are read back from device
        here — an armed journal pays that transfer on the commit side;
        the disarmed path never reaches this method."""
        mode = self.config.mode
        fwk, live = prep.fwk, prep.live
        kind, payload = prep.journal_input or ("unknown", None)
        kernel_backend = (self._gang_backend(prep) if mode == "gang"
                          else "lax")
        hard_w = float(fwk.hard_pod_affinity_weight)
        placements: Dict[str, str] = {}
        blocking: Dict[str, int] = {}
        scheduled = failed = 0
        for i, qp in enumerate(live):
            o = outcomes[i] if i < len(outcomes) else None
            node = o.node if o is not None else ""
            placements[qp.pod.metadata.name] = node
            if node:
                scheduled += 1
            else:
                failed += 1
                info = (audit_rows or {}).get(qp.pod.uid, {})
                for plugin in info.get("blocking", []):
                    blocking[plugin] = blocking.get(plugin, 0) + 1
        host_reasons: Dict[str, int] = {}
        for counts in prep.host_reject.values():
            for reason, n in counts.items():
                host_reasons[reason] = host_reasons.get(reason, 0) + n
        flight = prep.trace.rec
        record = {
            "v": ujournal.RECORD_VERSION,
            "seq": jr_seq,
            "cycle": self.cycle_count,
            "ts": time.time(),
            "mode": mode,
            "profile": fwk.profile_name,
            # ---- inputs ----
            "input": kind,
            "input_payload": payload,
            "batch": prep.batch,
            "cfg": prep.cfg,
            "host_ok": (np.asarray(prep.host_ok_dev)
                        if prep.host_ok_dev is not None else None),
            "score_bias": (np.asarray(prep.score_bias)
                           if prep.score_bias is not None else None),
            "needs_topo": bool(prep.needs_topo),
            "rng_counter": int(prep.journal_rng),
            "start_index": int(prep.journal_start),
            "kernel_backend": kernel_backend,
            "hard_pod_affinity_weight": hard_w,
            "mesh": self._mesh is not None,
            "vocab_sig": _vocab_caps(prep.builder.table),
            "n_nodes": len(prep.node_infos),
            # node row order only on anchor records — delta/chain records
            # provably keep it (a node-set change forces a resync)
            "node_names": ([ni.node_name for ni in prep.node_infos]
                           if kind == "resync" else None),
            "config_digest": ujournal.config_digest(
                mode, fwk.profile_name, prep.cfg, hard_w,
                self.config.kernel_backend),
            # ---- outputs ----
            "packed": np.asarray(packed),
            "rounds": (self.last_gang_rounds if mode == "gang" else 0),
            "pods": [(qp.pod.metadata.name, qp.pod.namespace, qp.pod.uid)
                     for qp in live],
            "placements": placements,
            "verdicts": {"scheduled": scheduled, "failed": failed,
                         "blocking": blocking,
                         "host_reasons": host_reasons},
            # ---- linkage ----
            "links": {
                "flight_seq": int(flight.seq) if flight is not None else 0,
                "decision_cycle": self.cycle_count,
                "ring_slot": int(prep.ring_slot),
                "pipeline_depth": int(self._pipeline.depth
                                      if self.config.pipeline_cycles
                                      else 1),
            },
        }
        jr.append(record)

    def _sync_journal_metrics(self) -> None:
        """Fold the armed journal's counters into scheduler_journal_*
        (serving thread only, like _sync_chaos_metrics); disarmed this
        is one attribute read."""
        jr = ujournal.journal()
        if jr is None or self.metrics is None:
            return
        records, dropped = jr.counters()
        seen_r, seen_d = self._journal_seen
        if records > seen_r:
            self.metrics.journal_records.inc(amount=records - seen_r)
        if dropped > seen_d:
            self.metrics.journal_dropped.inc(amount=dropped - seen_d)
        self._journal_seen = (max(records, seen_r), max(dropped, seen_d))
        self.metrics.journal_bytes.set(jr.disk_bytes())

    def _sync_chaos_metrics(self) -> None:
        """Fold the armed chaos registry's fire counts into
        scheduler_faults_injected_total (serving thread only, like
        _sync_flight_dropped); disarmed this is one attribute read."""
        reg = uchaos.active()
        if reg is None or self.metrics is None:
            return
        for point, n in reg.counts().items():
            seen = self._chaos_seen.get(point, 0)
            if n > seen:
                self.metrics.faults_injected.inc(point, amount=n - seen)
                self._chaos_seen[point] = n

    def _sync_flight_dropped(self) -> None:
        """Fold new flight-recorder ring drops into the monotonic metric
        counter — called right after each cycle record commits (serving
        thread only, so the seen-count needs no lock)."""
        self._sync_chaos_metrics()
        self._sync_journal_metrics()
        fr = utrace.flight_recorder()
        if fr is None or self.metrics is None:
            return
        dropped = fr.dropped()
        if dropped > self._flight_dropped_seen:
            self.metrics.flight_recorder_dropped.inc(
                amount=dropped - self._flight_dropped_seen)
        if dropped != self._flight_dropped_seen:
            # < happens when the ring was cleared/re-armed mid-run
            self._flight_dropped_seen = dropped

    def _schedule_with_extenders(self, fwk: Framework, live, states,
                                 node_infos, cluster, batch, cfg,
                                 host_ok, cycle_ctx=None,
                                 score_bias=None) -> List[ScheduleOutcome]:
        """Extender path (reference: generic_scheduler.go:497
        findNodesThatPassExtenders + :674-706 extender Prioritize combine):
        one batch filter+score on device, then per pod the HTTP webhooks
        refine feasibility/scores and selection happens host-side.
        score_bias: the [B, N] weighted host Score plugin totals from
        _prepare_group — added to the device totals BEFORE the extender
        Prioritize combine, so host Score plugins are honored identically
        with and without extenders configured."""
        from .extender import MAX_EXTENDER_PRIORITY, ExtenderError
        import random
        if self._mesh is not None:
            from .parallel import mesh as pmesh
            res = pmesh.sharded_filter_and_score(cluster, batch, cfg,
                                                 self._mesh, host_ok=host_ok)
        else:
            res = programs.filter_and_score(
                cluster, batch, cfg,
                self._jax.numpy.asarray(host_ok) if host_ok is not None
                else None)
        # ONE batched readback for the whole group, then Python lists: a
        # per-element float(scores[i, j]) in the per-pod loop below would
        # box B x N numpy scalars (and, pre-np.asarray, would cost one
        # device sync each — the kubelint host-sync/loop-readback trap)
        feasible = np.asarray(res.feasible).tolist()
        score_arr = np.asarray(res.scores)
        if score_bias is not None:
            score_arr = score_arr + np.asarray(score_bias)
        scores = score_arr.tolist()
        self.cycle_count += 1
        n_nodes = len(node_infos)
        row_of_node = {ni.node_name: j for j, ni in enumerate(node_infos)}
        outcomes: List[ScheduleOutcome] = []
        for i, qp in enumerate(live):
            state = states[qp.pod.uid]
            row_feas = feasible[i]
            names = [node_infos[j].node_name for j in range(n_nodes)
                     if row_feas[j]]
            # the device mask is pre-batch: re-check fit against the LIVE
            # node usage (includes earlier same-batch assumes) so two pods
            # in one extender batch cannot oversubscribe a node
            pod_res = PodInfo(qp.pod).resource
            names = [n for n in names
                     if self._fits_live(pod_res, self.cache.node_fit_view(n))]
            row_scores = scores[i]
            dev_score = {node_infos[j].node_name: row_scores[j]
                         for j in range(n_nodes) if row_feas[j]}
            exts = [e for e in self.extenders if e.is_interested(qp.pod)]
            err = None
            ext_info: Dict[str, str] = {}
            try:
                for e in exts:
                    before = len(names)
                    names, _ = e.filter(qp.pod, names)
                    # an extender may echo names outside the device-feasible
                    # set (stale cache, typo) — never let those through
                    names = [n for n in names if n in dev_score]
                    ext_info[e.url_prefix or "extender"] = (
                        f"filter {before} -> {len(names)} nodes")
                    if not names:
                        break
            except ExtenderError as ex:
                err = f"extender filter failed: {ex}"
            if err is not None:
                outcomes.append(self._fail(fwk, qp, state, "", err,
                                           preemption_may_help=False))
                self._record_decision(qp.pod, "unschedulable", message=err,
                                      extenders=ext_info)
                continue
            if not names:
                outcomes.append(self._fail(
                    fwk, qp, state, "", f"0/{n_nodes} nodes are available",
                    cycle=cycle_ctx))
                self._record_decision(
                    qp.pod, "unschedulable",
                    message=f"0/{n_nodes} nodes are available",
                    extenders=ext_info)
                continue
            combined = {n: 0.0 for n in names}
            try:
                for e in exts:
                    for n, s in e.prioritize(qp.pod, names).items():
                        if n in combined:
                            combined[n] += s
            except ExtenderError as ex:
                outcomes.append(self._fail(fwk, qp, state, "",
                                           f"extender prioritize failed: {ex}",
                                           preemption_may_help=False))
                self._record_decision(
                    qp.pod, "unschedulable",
                    message=f"extender prioritize failed: {ex}",
                    extenders=ext_info)
                continue
            scale = fw.MAX_NODE_SCORE / MAX_EXTENDER_PRIORITY
            totals = {n: dev_score[n] + combined[n] * scale for n in names}
            best = max(totals.values())
            ties = [n for n in names if totals[n] == best]
            self._rng_counter += 1
            node_name = random.Random(self._rng_counter).choice(ties)

            binders = [e for e in exts if e.is_binder()]
            binder = None
            if binders:
                def binder(pod, node, _b=binders[0]):
                    _b.bind(pod, node)
            outcome = self._commit(fwk, qp, state, node_name, len(names),
                                   binder_override=binder)
            if outcome.node and cycle_ctx is not None:
                cycle_ctx.note_commit(i, row_of_node[node_name])
            self._record_decision(
                qp.pod, "scheduled" if outcome.node else "unschedulable",
                node=outcome.node, message=outcome.err or "",
                n_feasible=len(names), extenders=ext_info)
            outcomes.append(outcome)
        return outcomes

    @staticmethod
    def _batch_topo_keys(table, pinfos) -> Tuple[int, ...]:
        """Topology-key vocab ids used by the batch's term sets — the
        static key set the same-pair matmul kernels iterate (a superset of
        every key in the batch per the ProgramConfig contract; cluster-side
        term paths use per-term pair gathers and need no key loop)."""
        keys = set()
        get = table.topokey.get
        for pi in pinfos:
            for term in pi.required_affinity_terms:
                keys.add(get(term.topology_key))
            for term in pi.required_anti_affinity_terms:
                keys.add(get(term.topology_key))
            for w in pi.preferred_affinity_terms:
                keys.add(get(w.term.topology_key))
            for w in pi.preferred_anti_affinity_terms:
                keys.add(get(w.term.topology_key))
            for c in pi.pod.spec.topology_spread_constraints:
                keys.add(get(c.topology_key))
        keys.discard(-1)
        return tuple(sorted(keys))

    def _nominated_overlay_mask(self, fwk, builder, cluster, batch, live,
                                node_infos, batch_topo_keys=()):
        """[B, N] bool DEVICE array — False where a pod would not fit once
        equal-or-greater-priority NOMINATED pods are counted as running on
        their nominated nodes (reference: addNominatedPods,
        core/generic_scheduler.go:530; the overlay-free second pass is the
        main filter program).  Covers BOTH dimensions of AddPod: resource
        capacity (nominated_fit_mask) and topology terms — nominated pods'
        labels and required anti-affinity repel, and their label counts
        skew PodTopologySpread (nominated_topology_mask).  A nominated pod
        that is itself in the batch reserves capacity against every OTHER
        row, never its own; batch-member nominated pods are excluded from
        the topology overlay (per-row self-exclusion is not expressible in
        one pass — documented bounded deviation).  None when no nominated
        pod is relevant."""
        from .models.batch import build_nominated
        uid_to_row = {qp.pod.uid: i for i, qp in enumerate(live)}
        node_row = {ni.node_name: j for j, ni in enumerate(node_infos)}
        entries = []
        for pod, nn in self.queue.all_nominated():
            row = node_row.get(nn)
            if row is None:
                continue
            entries.append((PodInfo(pod), row, uid_to_row.get(pod.uid, -1)))
        if not entries:
            return None
        nom = build_nominated(entries, builder.table)
        mask = programs.nominated_fit_mask(cluster, batch, nom)

        # topology overlay: only when the profile runs topology filters and
        # some term could actually interact
        topo_filters = {"InterPodAffinity", "PodTopologySpread"}
        topo_entries = [(pi, row) for pi, row, sr in entries if sr < 0]
        if topo_entries and (topo_filters & set(fwk.tensor_filters)):
            from .framework.types import (pod_with_affinity,
                                          pod_with_required_anti_affinity)
            interacts = (
                any(pod_with_affinity(qp.pod)
                    or qp.pod.spec.topology_spread_constraints
                    for qp in live)
                or any(pod_with_required_anti_affinity(pi.pod)
                       for pi, _ in topo_entries))
            if interacts:
                jnp = self._jax.numpy
                nom_pb = PodBatchBuilder(builder.table).build(
                    [pi for pi, _ in topo_entries])
                nom_pb = self._jax.tree.map(np.asarray, nom_pb)
                M = np.asarray(nom_pb.valid).shape[0]
                rows = np.full((M,), -1, np.int32)
                prio = np.zeros((M,), np.int32)
                for i, (pi, row) in enumerate(topo_entries):
                    rows[i] = row
                    prio[i] = pi.pod.priority()
                active = tuple(sorted(
                    set(batch_topo_keys)
                    | set(self._batch_topo_keys(
                        builder.table, [pi for pi, _ in topo_entries]))))
                topo_mask = programs.nominated_topology_mask(
                    cluster, nom_pb, jnp.asarray(rows), jnp.asarray(prio),
                    batch, programs.ProgramConfig(
                        filters=fwk.tensor_filters, scores=(),
                        hostname_topokey=max(
                            builder.table.topokey.get(api.LABEL_HOSTNAME),
                            0),
                        active_topo_keys=active))
                mask = mask & topo_mask
        return mask

    @staticmethod
    def _fits_live(pod_res, view) -> bool:
        """NodeResourcesFit essentials against a live fit view
        (cache.node_fit_view: allocatable, requested, pod count;
        reference: noderesources/fit.go:194-267): pod count always, the
        standard channels and scalars only when requested."""
        if view is None:
            return False
        alloc, req, n_pods = view
        if n_pods + 1 > alloc.allowed_pod_number:
            return False
        r = pod_res
        if r.milli_cpu > 0 and r.milli_cpu > alloc.milli_cpu - req.milli_cpu:
            return False
        if r.memory > 0 and r.memory > alloc.memory - req.memory:
            return False
        if (r.ephemeral_storage > 0 and r.ephemeral_storage
                > alloc.ephemeral_storage - req.ephemeral_storage):
            return False
        for k, v in r.scalar_resources.items():
            if v > 0 and v > (alloc.scalar_resources.get(k, 0)
                              - req.scalar_resources.get(k, 0)):
                return False
        return True

    # ------------------------------------------------------------------ commit

    def _commit(self, fwk: Framework, qp: QueuedPodInfo, state: CycleState,
                node_name: str, n_feasible: int,
                binder_override=None, pinfo: Optional[PodInfo] = None,
                host_relevant: Optional[bool] = None,
                flight=None, slo=None) -> ScheduleOutcome:
        pod = qp.pod
        if host_relevant is None:
            host_relevant = fwk.has_relevant_host_filters(pod)
        # Commit-time host-filter re-check: the pre-batch host_ok mask was
        # computed before any same-batch pod was assumed, so two same-batch
        # pods could exceed a host-checked per-node limit (e.g. attachable
        # volumes).  Re-validate against the cache's LIVE NodeInfo — which
        # includes earlier same-batch assumes — before reserving.  The
        # reference's serial loop gets this by construction
        # (scheduler.go:509: every pod filters against assumed state).
        if host_relevant:
            ni = self.cache.node_info(node_name)
            if ni is not None:
                st = fwk.run_filter_plugins(state, pod, ni)
                if not st.is_success():
                    # other nodes may still fit next cycle; don't preempt
                    # on a stale single-node verdict
                    return self._fail(fwk, qp, state, node_name,
                                      st.message() or
                                      "commit-time filter re-check failed",
                                      preemption_may_help=False)
        # Reserve (reference: scheduler.go:586).  Commit-phase failures are
        # not FitErrors, so they never trigger preemption
        # (reference: scheduler.go:542 err type check).
        st = fwk.run_reserve_plugins(state, pod, node_name)
        if not st.is_success():
            fwk.run_unreserve_plugins(state, pod, node_name)
            return self._fail(fwk, qp, state, node_name, st.message(),
                              preemption_may_help=False)

        # assume (reference: scheduler.go:435,593).  A shallow clone with a
        # fresh spec is enough: the cache reads spec/containers/labels,
        # which the scheduler never mutates — the deep copy burned ~1.5s
        # per 4k-pod cycle for nothing.
        assumed = copy.copy(pod)
        assumed.spec = copy.copy(pod.spec)
        assumed.spec.node_name = node_name
        try:
            self.cache.assume_pod(
                assumed,
                pinfo.with_pod(assumed) if pinfo is not None else None)
        except ValueError as e:
            fwk.run_unreserve_plugins(state, pod, node_name)
            return self._fail(fwk, qp, state, node_name, str(e),
                              preemption_may_help=False)

        # Permit (reference: scheduler.go:608)
        st = fwk.run_permit_plugins(state, pod, node_name)
        if not st.is_success() and st.code != Code.WAIT:
            self._forget(assumed)
            fwk.run_unreserve_plugins(state, pod, node_name)
            return self._fail(fwk, qp, state, node_name, st.message(),
                              preemption_may_help=False)

        # binding cycle (reference: scheduler.go:628 goroutine)
        if self._async_binding:
            try:
                fut = self._bind_pool.submit(self._bind_cycle, fwk, qp,
                                             state, assumed, node_name,
                                             binder_override, flight, slo)
            except RuntimeError:
                # close() raced the serving loop and shut the pool down
                # mid-cycle: bind synchronously so the placement still
                # lands instead of panicking the cycle
                err = self._bind_cycle(fwk, qp, state, assumed, node_name,
                                       binder_override, flight, slo)
            else:
                # prune completed futures so a long-running scheduler
                # doesn't retain one CycleState + pod copy per pod
                self._inflight_binds = [f for f in self._inflight_binds
                                        if not f.done()]
                self._inflight_binds.append(fut)
                err = None
        else:
            err = self._bind_cycle(fwk, qp, state, assumed, node_name,
                                   binder_override, flight, slo)
        return ScheduleOutcome(pod=pod, node=node_name if err is None else "",
                               err=err, n_feasible=n_feasible)

    def _bind_cycle(self, fwk: Framework, qp: QueuedPodInfo, state: CycleState,
                    assumed: api.Pod, node_name: str,
                    binder_override=None, flight=None,
                    slo=None) -> Optional[str]:
        """reference: scheduler.go:628-687.  flight: the cycle's
        CycleRecord — per-pod bind spans land on it from whichever thread
        runs the bind (capped per record; None when disarmed).  slo: the
        pod's cycle-side stage vector (_slo_prefix) — the bind completes
        it with commit/bind/e2e and records the terminal pod (None when
        the tracker is disarmed)."""
        if flight is not None:
            with flight.span("bind", pod=qp.pod.metadata.name,
                             node=node_name):
                return self._bind_cycle_inner(fwk, qp, state, assumed,
                                              node_name, binder_override,
                                              slo)
        return self._bind_cycle_inner(fwk, qp, state, assumed, node_name,
                                      binder_override, slo)

    def _bound_node(self, pod: api.Pod):
        """The API's current view of a pod's binding: the node name,
        "" when the pod exists unbound, None when the pod is gone (or
        the store is unreadable — the ladder treats unknown as gone and
        stops; the pod's failure path requeues it anyway).  Best-effort:
        a REST mirror that lags just defers the verdict one attempt."""
        try:
            cur = self.store.get_pod(pod.namespace, pod.metadata.name)
        except Exception:
            return None
        return None if cur is None else (cur.spec.node_name or "")

    def _bind_cycle_inner(self, fwk: Framework, qp: QueuedPodInfo,
                          state: CycleState, assumed: api.Pod,
                          node_name: str, binder_override=None,
                          slo=None) -> Optional[str]:
        pod = qp.pod
        st = fwk.wait_on_permit(pod)
        if not st.is_success():
            self._forget(assumed)
            fwk.run_unreserve_plugins(state, pod, node_name)
            self._record_failure(fwk, qp, st.message())
            return st.message() or "permit rejected"
        st = fwk.run_pre_bind_plugins(state, pod, node_name)
        if not st.is_success():
            self._forget(assumed)
            fwk.run_unreserve_plugins(state, pod, node_name)
            self._record_failure(fwk, qp, st.message())
            return st.message() or "prebind failed"
        bind_start = utrace.wallclock()
        if binder_override is not None:
            # extender binding (reference: scheduler.go:457 extendersBinding)
            try:
                binder_override(pod, node_name)
                st = Status.success()
            except Exception as e:
                st = Status.error(f"extender bind failed: {e}")
        else:
            st = fwk.run_bind_plugins(state, pod, node_name)
            # transient-bind retry ladder: a bind transport ERROR (socket
            # hiccup, injected chaos "bind" fault) retries in place on
            # the thread that ran bind (the binder pool under async
            # binding, the serving loop otherwise), sleeping the pod
            # backoff ladder between attempts (pod_initial_backoff_seconds
            # doubling, capped) — the cycle already won this placement; a
            # once-flaky API server must not cost it.  Each attempt is
            # gated on the API's CURRENT state, never on error-message
            # classification: bind is NOT idempotent (BindingREST rejects
            # any re-bind, even to the same node), so a bind that LANDED
            # with a lost response resolves to success without a re-POST,
            # and a pod that is gone or bound elsewhere stops the ladder
            # immediately — deterministic failures never sleep it.  Only
            # DefaultBinder's exception path ("binding rejected: ...")
            # enters at all; config errors fail as before.
            retries = max(int(getattr(self.config, "bind_retries", 0)), 0)
            delay = min(self.config.pod_initial_backoff_seconds,
                        self.config.pod_max_backoff_seconds)
            attempt = 0
            while (not st.is_success() and attempt < retries
                   and st.message().startswith("binding rejected:")):
                bound = self._bound_node(pod)
                if bound == node_name:
                    # applied-but-response-lost: already bound right
                    st = Status.success()
                    attempt += 1     # counts as a recovered attempt
                    break
                if bound != "":
                    # gone (None) or bound elsewhere: permanent — the
                    # normal failure path handles it, no sleeps owed
                    break
                attempt += 1
                time.sleep(delay)
                delay = min(delay * 2,
                            self.config.pod_max_backoff_seconds)
                st = fwk.run_bind_plugins(state, pod, node_name)
            if attempt and st.is_success():
                if self.metrics is not None:
                    self.metrics.recoveries.inc("bind-retry")
                if self.recorder:
                    self.recorder.event(
                        pod, "Normal", "BindRetried",
                        f"bind succeeded after {attempt} retr"
                        f"{'y' if attempt == 1 else 'ies'}")
        if not st.is_success():
            self._forget(assumed)
            fwk.run_unreserve_plugins(state, pod, node_name)
            self._record_failure(fwk, qp, st.message())
            return st.message() or "bind failed"
        self.cache.finish_binding(assumed)
        fwk.run_post_bind_plugins(state, pod, node_name)
        if self.metrics:
            now = utrace.wallclock()
            self.metrics.binding_duration.observe(now - bind_start)
            self.metrics.pod_scheduled(
                qp.attempts, now - qp.initial_attempt_timestamp,
                now - qp.timestamp)
        if slo is not None:
            trk = uslo.tracker()
            if trk is not None:
                self._slo_observe_terminal(trk, slo, qp, "bound",
                                           bind_start=bind_start)
        if self.recorder:
            self.recorder.event(pod, "Normal", "Scheduled",
                                f"Successfully assigned "
                                f"{pod.namespace}/{pod.metadata.name} to "
                                f"{node_name}")
        return None

    def _forget(self, assumed: api.Pod) -> None:
        # a rolled-back placement invalidates the chained cluster (it may
        # already carry this pod's usage); one locked block so a concurrent
        # _prepare_group can never see the seq bump without the None
        with self._chain_lock:
            self._chain = None
            self._chain_seq += 1
        self._drop_chain_residency()
        try:
            self.cache.forget_pod(assumed)
        except ValueError:
            pass

    # ------------------------------------------------------------------ failure

    def _fail(self, fwk: Framework, qp: QueuedPodInfo, state: CycleState,
              node_name: str, message: str,
              preemption_may_help: bool = True,
              cycle=None) -> ScheduleOutcome:
        """reference: scheduler.go:391 recordSchedulingFailure +
        :542-563 — preemption now runs behind the PostFilter extension
        point (framework.go:516; DefaultPreemption)."""
        pod = qp.pod
        nominated = ""
        if preemption_may_help and fwk.post_filter_plugins:
            from .plugins.intree import DefaultPreemption
            if cycle is not None:
                state.write(DefaultPreemption.CYCLE_CONTEXT_KEY, cycle)
            result, st = fwk.run_post_filter_plugins(state, pod)
            if st.is_success() and result is not None:
                nominated = result.nominated_node_name
        self._record_failure(fwk, qp, message, nominated)
        return ScheduleOutcome(pod=pod, node="", err=message,
                               preemption_may_help=preemption_may_help)

    def _record_failure(self, fwk: Framework, qp: QueuedPodInfo,
                        message: str, nominated_node: str = "") -> None:
        pod = qp.pod
        if nominated_node:
            # requeueing re-registers the pod with the nominator from
            # pod.status (queue._add fallback); carry the fresh nomination
            # so it survives (reference: scheduler.go:352 — the API update
            # and queue re-add both see NominatedNodeName)
            pod.status.nominated_node_name = nominated_node
        try:
            # use the cycle captured at pop, not the current counter — pods
            # popped later in the same batch must not mask a move request
            # that raced with this pod's scheduling attempt (reference:
            # scheduler.go:515,559 podSchedulingCycle)
            self.queue.add_unschedulable_if_not_present(
                qp, qp.scheduling_cycle)
        except ValueError:
            pass
        if self.recorder:
            self.recorder.event(pod, "Warning", "FailedScheduling", message)
        try:
            self.store.update_pod_condition(
                pod,
                api.PodCondition(type=api.POD_SCHEDULED, status="False",
                                 reason=api.REASON_UNSCHEDULABLE,
                                 message=message),
                nominated_node_name=nominated_node)
        except Exception:
            pass
        if self.metrics:
            self.metrics.pod_unschedulable()

    # ------------------------------------------------------------------ audit

    def _record_decision(self, pod: api.Pod, outcome: str, **kw) -> None:
        """Fold one pod's (un)scheduling decision into the bounded
        DecisionLog (no-op with KUBETPU_AUDIT=0 — no lock taken)."""
        if not self.decisions.enabled:
            return
        self.decisions.record(PodDecision(
            name=pod.metadata.name, namespace=pod.namespace, uid=pod.uid,
            outcome=outcome, cycle=self.cycle_count, **kw))

    def _audit_failures(self, prep: PreparedCycle, qpods) -> Dict[str, Dict]:
        """Per-plugin attribution for this cycle's failed pods: ONE
        explain_verdicts dispatch + ONE packed [2F+3, B] readback against
        the cycle-start snapshot (models/programs.py).  Like the
        preemption wave's what-if, this is a SECOND device sync on
        cycles that have failures — the retry-churn dedup in
        _commit_group bounds it to cycles whose failed set or committed
        state actually changed.  Returns uid -> PodDecision kwargs; also
        bumps scheduler_framework_rejections_total{plugin} for each pod's
        blocking plugin(s).  Any failure degrades to no attribution — the
        audit must never fail a cycle."""
        ds = udevstats.devstats()
        t_ev = 0.0
        try:
            # devstats timer starts AFTER the jitted call returns (the
            # dispatch is async but trace/compile happen synchronously
            # inside it — a first-call compile must not pollute the
            # measured device time, same discipline as the fence)
            out_dev = programs.explain_verdicts(
                prep.cluster, prep.batch, prep.cfg, prep.host_ok_dev)
            t_ev = time.perf_counter() if ds is not None else 0.0
            packed = np.asarray(out_dev)
        except Exception:
            import logging
            logging.getLogger("kubetpu").warning(
                "decision audit failed; failures recorded unattributed",
                exc_info=True)
            return {}
        if ds is not None and t_ev:
            # the audit's packed readback is already a natural device
            # sync, so the per-program measurement is free — recorded
            # on every armed failure cycle, no fence needed
            ds.record_program(
                "explain_verdicts", time.perf_counter() - t_ev,
                source="sync",
                in_bytes=udevstats.pytree_nbytes((prep.cluster,
                                                  prep.batch)))
        filters = prep.cfg.filters
        F = len(filters)
        counts = packed[:F].tolist()
        blocking = packed[F:2 * F].tolist()
        no_feas = packed[2 * F].tolist()
        best_node = packed[2 * F + 1].tolist()
        best_score = packed[2 * F + 2].tolist()
        node_infos = prep.node_infos
        out: Dict[str, Dict] = {}
        for qp in qpods:
            row = prep.cycle_ctx.row_of.get(qp.pod.uid)
            if row is None:
                continue
            rej = {filters[f]: counts[f][row]
                   for f in range(F) if counts[f][row]}
            blk = [filters[f] for f in range(F) if blocking[f][row]]
            info: Dict[str, object] = {"rejections": rej, "blocking": blk}
            if not no_feas[row] and best_node[row] >= 0:
                # feasible at cycle start — lost to in-batch contention;
                # name the node it would have scored best on
                info["best_node"] = node_infos[best_node[row]].node_name
                info["best_score"] = (best_score[row]
                                      / programs.SCORE_SCALE)
            if self.metrics is not None:
                attributed = blk
                if not attributed and no_feas[row] and rej:
                    # no single filter blocks alone (joint infeasibility):
                    # attribute to the one failing the most nodes
                    attributed = [max(rej, key=rej.get)]
                for plugin in attributed:
                    self.metrics.framework_rejections.inc(plugin)
            out[qp.pod.uid] = info
        return out

    # ------------------------------------------------------------------ loop

    def prewarm(self, ladder_steps: Optional[int] = None) -> bool:
        """Compile the serving program for the CURRENT cluster shape before
        the first pod arrives (VERDICT r3 #7: first-cycle compile was ~6
        cycles of latency).  Builds the real snapshot plus a synthetic
        full-bucket pod batch whose labels are sampled from pods already in
        the cluster (so vocab caps match what real pending pods of the same
        workloads will produce), runs the device program once, and discards
        the result — nothing is assumed, bound or queued.  With the
        persistent XLA cache the compile is loaded, not re-run; cold, it
        happens HERE instead of under the first scheduled pod.
        ladder_steps > 0 additionally dry-runs that many chained cycles so
        the pod-axis bucket ladder a growing cluster will traverse is
        AOT-compiled (see _prewarm_ladder); (bucket, seconds) pairs land
        in self.prewarm_report.  Returns True if a program was warmed.

        AOT-ARTIFACT fast path: when a serve-mode aot runtime is armed
        (KUBETPU_AOT_DIR) and its index carries serving-family rows, the
        build-time serialized executables are deserialize-and-loaded UP
        FRONT, and the dry-run below then dispatches into the resident
        executables — no trace, no lower, no XLA for covered call forms;
        restart cost drops from XLA time to disk-load + one execution.
        The dry-run is NOT skipped: anything the artifact set does not
        cover (a mesh profile's sharded twins, a bucket the set pruned, a
        cfg drift since build) still gets compiled here exactly as if no
        artifacts were armed — arming can never reintroduce the
        first-cycle stall class prewarm exists to prevent."""
        if ladder_steps is None:
            ladder_steps = getattr(self.config, "prewarm_ladder", 0)
        from .utils import aot as _aot
        rt = _aot.active_runtime()
        if rt is not None and rt.mode == "serve":
            self._prewarm_aot(rt)
        fwk = next(iter(self.profiles.values()))
        # a PRIVATE snapshot: the ladder variant runs on a background
        # thread, and mutating the serving loop's self.snapshot from there
        # would race _prepare_group's lock-free node_info_list read
        snap = Snapshot()
        self.cache.update_snapshot(snap)
        node_infos = snap.node_info_list
        if not node_infos:
            return False
        # one synthetic proto per DISTINCT label set sampled from the
        # cluster's pods: the compiled program's shapes include the
        # selector-dedup bucket (U unique selectors), so a single-proto
        # batch (U=1) compiles a DIFFERENT program than a real wave of
        # e.g. 16 app groups (U bucket 32) — prewarm must reproduce the
        # workload's selector diversity or the first real cycle pays the
        # compile anyway
        distinct: Dict[tuple, dict] = {}
        for ni in node_infos:
            if len(distinct) >= 63:
                break
            for pi in ni.pods:
                labels = pi.pod.metadata.labels
                if labels:
                    distinct.setdefault(tuple(sorted(labels.items())),
                                        dict(labels))
                if len(distinct) >= 63:
                    break
        label_sets = list(distinct.values()) or [{}]
        # pad diversity to 31 distinct selector groups: the compiled
        # program keys on the pow2 UNIQUE-selector bucket, and incoming
        # waves are usually more diverse than the possibly-uniform
        # existing pods (e.g. a 16-replica-set wave dedups to bucket 32).
        # Warming the 32-bucket covers 17..32 unique selectors — the
        # common workload shape; rarer diversities still fall back to the
        # persistent cache.
        while len(label_sets) < 31:
            label_sets.append({"kubetpu-prewarm": f"g{len(label_sets)}"})

        def proto_for(idx: int, labels: dict) -> api.Pod:
            p = api.Pod(
                metadata=api.ObjectMeta(name=f"prewarm-{idx}",
                                        namespace="default",
                                        labels=dict(labels)),
                spec=api.PodSpec(containers=[api.Container(
                    name="c", image="",
                    resources=api.ResourceRequirements(
                        requests={"cpu": "1m", "memory": "1Mi"}))]))
            # topology terms make the warmed gang variant
            # intra_batch_topology=True — the serving default; selectors
            # mirror the replica-set pattern (select own labels)
            sel = api.LabelSelector(
                match_labels=dict(labels) or {"kubetpu-prewarm": "x"})
            p.spec.affinity = api.Affinity(
                pod_anti_affinity=api.PodAntiAffinity(
                    required_during_scheduling_ignored_during_execution=[
                        api.PodAffinityTerm(
                            label_selector=sel,
                            topology_key=api.LABEL_HOSTNAME)]))
            # a zone soft-spread makes the warmed active-key set
            # {hostname, zone} — what typical serving batches use
            p.spec.topology_spread_constraints.append(
                api.TopologySpreadConstraint(
                    max_skew=1, topology_key=api.LABEL_ZONE,
                    when_unsatisfiable="ScheduleAnyway",
                    label_selector=sel))
            return p

        protos = [PodInfo(proto_for(i, ls))
                  for i, ls in enumerate(label_sets)]
        B_warm = min(self.config.batch_size, 1024)
        pinfos = [protos[i % len(protos)] for i in range(B_warm)]
        builder = SnapshotBuilder(
            hard_pod_affinity_weight=fwk.hard_pod_affinity_weight)
        builder.intern_pending(protos)
        cluster = builder.build(node_infos).to_device()
        pb = PodBatchBuilder(builder.table)
        batch = self._jax.tree.map(np.asarray, pb.build(pinfos))
        cfg = programs.ProgramConfig(
            filters=fwk.tensor_filters, scores=fwk.tensor_scores,
            hostname_topokey=max(builder.table.topokey.get(api.LABEL_HOSTNAME), 0),
            plugin_args=fwk.tensor_plugin_args(builder.table),
            active_topo_keys=self._batch_topo_keys(builder.table,
                                                   protos[:1]))
        rng = self._jax.random.PRNGKey(0)
        # profiles with host score plugins serve with a [B, N] bias array;
        # warming the bias=None variant alone would leave the serving
        # shape to compile under the first real cycle
        warm_bias = None
        if fwk.host_score_plugins:
            warm_bias = self._jax.numpy.zeros(
                (batch.valid.shape[0], cluster.allocatable.shape[0]),
                self._jax.numpy.float32)
        # flight-recorder linkage: prewarm gets its OWN cycle record (it
        # runs outside any scheduling cycle) so /debug/flightz and
        # traceview show restart cost — one "prewarm" span per bucket,
        # "aot-load" spans (hit/miss, seconds) nested when the aot seams
        # resolve against a capture runtime
        import contextlib
        fr = utrace.flight_recorder()
        fr_rec = fr.begin_cycle("prewarm") if fr is not None else None
        t0 = time.time()
        with (fr_rec.span("prewarm", mode="dry-run") if fr_rec is not None
              else contextlib.nullcontext()) as sp:
            if self.config.mode == "gang":
                if self._mesh is not None:
                    from .parallel import mesh as pmesh
                    # score_bias=warm_bias like the single-chip branch: mesh
                    # profiles with host score plugins serve the bias-variant
                    # program, so prewarm must compile that variant or the
                    # first real cycle pays the compile stall (ADVICE r5)
                    res = pmesh.sharded_schedule_gang(cluster, batch, cfg,
                                                      rng, self._mesh,
                                                      score_bias=warm_bias)
                else:
                    from .models.gang import run_auction
                    res = run_auction(cluster, batch, cfg, rng,
                                      score_bias=warm_bias)
                    if self.config.kernel_backend == "pallas":
                        # term-free serving batches route
                        # intra_batch_topology=False + pallas — a DISTINCT
                        # compiled program; warm it or the first term-free
                        # cycle pays the megakernel compile stall
                        res_p = run_auction(cluster, batch, cfg, rng,
                                            score_bias=warm_bias,
                                            intra_batch_topology=False,
                                            kernel_backend="pallas")
                        np.asarray(res_p.packed)
            elif self._mesh is not None:
                from .parallel import mesh as pmesh
                res = pmesh.sharded_schedule_sequential(
                    cluster, batch, cfg, rng,
                    hard_pod_affinity_weight=float(
                        fwk.hard_pod_affinity_weight),
                    score_bias=warm_bias)
            else:
                res = schedule_sequential(
                    cluster, batch, cfg, rng,
                    hard_pod_affinity_weight=float(
                        fwk.hard_pod_affinity_weight),
                    score_bias=warm_bias)
            np.asarray(res.packed)   # wait out the compile
            if self.decisions.enabled:
                # the decision-audit program dispatches on the first failing
                # cycle; compile it HERE so an unschedulable pod cannot stall
                # the serving loop on the audit's compile (the VERDICT r4 #4
                # stall class prewarm exists to prevent).  BOTH jit variants:
                # host_ok=None and the [B, N] array signature _prepare_group
                # produces whenever host filters / volume masks / nominated
                # pods are in play.  Serving cycles with a different static
                # cfg (active_topo_keys) still fall back to the persistent
                # cache.
                try:
                    np.asarray(programs.explain_verdicts(cluster, batch,
                                                         cfg))
                    ones = self._jax.numpy.ones(
                        (batch.valid.shape[0],
                         cluster.allocatable.shape[0]), bool)
                    np.asarray(programs.explain_verdicts(
                        cluster, batch, cfg, host_ok=ones))
                except Exception:
                    import logging
                    logging.getLogger("kubetpu").warning(
                        "audit prewarm failed; first failing cycle pays "
                        "the compile", exc_info=True)
            if sp is not None:
                sp.args["bucket"] = int(cluster.pod_valid.shape[0])
                sp.args["seconds"] = round(time.time() - t0, 4)
        self.prewarm_report.append(
            (int(cluster.pod_valid.shape[0]), round(time.time() - t0, 2)))
        if ladder_steps and self.config.mode == "gang" \
                and self._mesh is None:
            self._prewarm_ladder(fwk, cluster, batch, cfg, rng, res,
                                 ladder_steps, warm_bias, fr_rec=fr_rec)
        if fr is not None and fr_rec is not None:
            fr.commit_cycle(fr_rec)
        return True

    def _prewarm_aot(self, rt) -> bool:
        """The serialized-artifact half of prewarm: deserialize-and-load
        every serving-family artifact the armed runtime's index carries
        (utils/aot.AotRuntime.preload) so the dry-run that FOLLOWS — and
        the first real cycle — dispatch into resident executables instead
        of tracing.  Returns True when anything loaded (informational;
        the caller runs the dry-run either way, which is what keeps an
        incomplete artifact set from being worse than no artifacts)."""
        import contextlib
        fr = utrace.flight_recorder()
        fr_rec = fr.begin_cycle("prewarm") if fr is not None else None
        t0 = time.time()
        with (fr_rec.span("prewarm", mode="aot-artifact")
              if fr_rec is not None else contextlib.nullcontext()) as sp:
            report = rt.preload()
            if sp is not None:
                sp.args["seconds"] = round(time.time() - t0, 4)
                sp.args["loaded"] = sum(1 for r in report if r["ok"])
        if fr is not None and fr_rec is not None:
            fr_rec.meta["aot"] = rt.stats()
            fr.commit_cycle(fr_rec)
        loaded = [r for r in report if r["ok"]]
        failed = len(report) - len(loaded)
        if failed and self.metrics is not None:
            # corrupt/unreadable artifacts degraded to the per-bucket
            # trace fallback (reasons in the preload report / aot-load
            # flight spans) — count them as recoveries, not silence
            self.metrics.recoveries.inc("aot-fallback", amount=failed)
        for r in loaded:
            self.prewarm_report.append(
                (int(r.get("pod_bucket") or 0), round(r["seconds"], 2)))
        if loaded:
            import logging
            logging.getLogger("kubetpu").info(
                "prewarm: %d aot artifacts loaded in %.2fs (%d failed; "
                "uncovered buckets fall back per dispatch)", len(loaded),
                time.time() - t0, len(report) - len(loaded))
        return bool(loaded)

    def _prewarm_ladder(self, fwk, cluster, batch, cfg, rng, res,
                        steps: int, warm_bias=None, fr_rec=None) -> None:
        """AOT-compile the pow2 bucket ladder a growing chained drain will
        traverse (VERDICT r4 #4: each new bucket stalled serving for tens
        of seconds).  Instead of guessing shapes, this DRY-RUNS the chain
        itself: materialize the synthetic placements with exactly the pad
        buckets _dispatch_group would use, re-run the auction on the grown
        cluster, repeat — every program a real drain of `steps` cycles
        needs is thereby compiled (or loaded from the persistent cache),
        and nothing is committed.  An armed aot runtime PRUNES the ladder:
        buckets the artifact set dropped (the flight recorder never saw
        them serve — tools/kubeaot --prune) are not worth the dry-run
        either."""
        import contextlib

        from .utils import aot as _aot
        from .utils.intern import pow2_bucket
        rt = _aot.active_runtime()
        B_cap = batch.valid.shape[0]
        ta = batch.raa.valid.shape[1]
        for _ in range(steps):
            p_next = int(cluster.pod_valid.shape[0]) + B_cap
            e_next = int(cluster.filter_terms.valid.shape[0]) + B_cap * ta
            if (rt is not None and rt.mode == "serve"
                    and not rt.allows_bucket(pow2_bucket(p_next))):
                # pruned bucket: the recorder's bucket-hit data says no
                # serving cycle ever reached it
                break
            t0 = time.time()
            _lsp = (fr_rec.span("prewarm", mode="ladder")
                    if fr_rec is not None else contextlib.nullcontext())
            with _lsp as sp:
                cluster, res = self._prewarm_ladder_step(
                    fwk, cluster, batch, cfg, rng, res, warm_bias,
                    p_next, e_next)
                if sp is not None:
                    sp.args["bucket"] = int(cluster.pod_valid.shape[0])
                    sp.args["seconds"] = round(time.time() - t0, 4)
            self.prewarm_report.append(
                (int(cluster.pod_valid.shape[0]),
                 round(time.time() - t0, 2)))
            # residency-ledger seam (utils/devstats.py): the ladder's
            # dry-run clusters are live HBM until GC — register the
            # deepest rung so restart-time residency is accountable
            if udevstats.devstats() is not None:
                udevstats.register_cluster(
                    "prewarm-ladder", fwk.profile_name, cluster,
                    int(cluster.allocatable.shape[0]),
                    meta={"bucket": int(cluster.pod_valid.shape[0])})

    def _prewarm_ladder_step(self, fwk, cluster, batch, cfg, rng, res,
                             warm_bias, p_next, e_next):
        """One dry-run rung: materialize the synthetic placements at the
        next pad buckets, re-run the auction (+ audit variants) on the
        grown cluster.  Returns (grown cluster, auction result)."""
        from .models.gang import materialize_assigned, run_auction
        from .utils.intern import pow2_bucket
        cluster = materialize_assigned(
            cluster, batch, res.chosen, res.requested, res.nz,
            res.ports_used, pad_pods_to=pow2_bucket(p_next),
            pad_terms_to=pow2_bucket(e_next), extend_score_terms=True,
            hard_pod_affinity_weight=float(
                fwk.hard_pod_affinity_weight))
        res = run_auction(cluster, batch, cfg, rng,
                          score_bias=warm_bias)
        np.asarray(res.packed)
        if self.config.kernel_backend == "pallas":
            res_p = run_auction(cluster, batch, cfg, rng,
                                score_bias=warm_bias,
                                intra_batch_topology=False,
                                kernel_backend="pallas")
            np.asarray(res_p.packed)
        if self.decisions.enabled:
            # audit program per pod-axis bucket, like the auction (a
            # drain's failures can land in any grown bucket); both
            # host_ok variants, matching the base prewarm
            try:
                np.asarray(programs.explain_verdicts(cluster, batch,
                                                     cfg))
                ones = self._jax.numpy.ones(
                    (batch.valid.shape[0],
                     cluster.allocatable.shape[0]), bool)
                np.asarray(programs.explain_verdicts(
                    cluster, batch, cfg, host_ok=ones))
            except Exception:
                pass
        return cluster, res

    def run(self) -> threading.Thread:
        """Start the serving loop (reference: scheduler.go:339 Run)."""
        self.queue.run()
        self.cache.run()
        import os
        if (getattr(self.config, "prewarm", True)
                and os.environ.get("KUBETPU_PREWARM", "1") != "0"):
            try:
                # current shape blocks startup (it gates the first cycle);
                # the bucket ladder compiles in the background
                self.prewarm(ladder_steps=0)
            except Exception:
                import logging
                logging.getLogger("kubetpu").warning(
                    "prewarm failed; first cycle pays the compile",
                    exc_info=True)
            steps = getattr(self.config, "prewarm_ladder", 0)
            if steps:
                def ladder():
                    try:
                        self.prewarm(ladder_steps=steps)
                    except Exception:
                        import logging
                        logging.getLogger("kubetpu").warning(
                            "ladder prewarm failed", exc_info=True)
                threading.Thread(target=ladder, daemon=True,
                                 name="kubetpu-prewarm-ladder").start()

        def loop():
            while not self._stop.is_set():
                try:
                    self.schedule_pending(timeout=0.2)
                except Exception:  # the serving loop must never die
                    # (reference: wait.UntilWithContext keeps scheduleOne
                    # running; per-pod errors go through
                    # recordSchedulingFailure, anything else is logged)
                    import logging
                    import traceback
                    logging.getLogger("kubetpu").error(
                        "scheduling cycle panicked:\n%s",
                        traceback.format_exc())
                    time.sleep(0.1)
        t = threading.Thread(target=loop, daemon=True,
                             name="kubetpu-scheduler")
        self._serve_thread = t
        t.start()
        return t

    def wait_for_inflight_binds(self, timeout: float = 10.0) -> None:
        deadline = time.time() + timeout
        for fut in list(self._inflight_binds):
            fut.result(timeout=max(0.0, deadline - time.time()))
        self._inflight_binds = [f for f in self._inflight_binds if not f.done()]

    def close(self) -> None:
        """Idempotent shutdown: stop the serving loop and JOIN it before
        flushing, so the pipeline flush cannot race a cycle in flight —
        if the loop outlives the join bound (a cold cycle can be paying a
        multi-second compile), the in-flight cycle is left to that loop
        and NOT flushed here.  Then close the queue (wakes blocked pops,
        joins flushers), the cache (joins cleanup), and the bind pool."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        t = self._serve_thread
        serve_loop_live = False
        if (t is not None and t is not threading.current_thread()
                and t.is_alive()):
            t.join(timeout=2.0)
            serve_loop_live = t.is_alive()
        self._serve_thread = None
        if not serve_loop_live:
            try:
                self.flush_pipeline()
            except Exception:
                pass
        self.queue.close()
        self.cache.close()
        self._bind_pool.shutdown(wait=False)
