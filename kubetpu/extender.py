"""HTTP extender: the legacy out-of-process scheduler webhook.

reference: pkg/scheduler/core/extender.go (HTTPExtender :42, Filter :273,
Prioritize :343, Bind :385, send :412, IsInterested :450) with wire types
from staging/src/k8s.io/kube-scheduler/extender/v1.  Filter runs serially
per extender after the device filter pass
(core/generic_scheduler.go:497 findNodesThatPassExtenders); Prioritize
results are weighted and added to the device scores
(:674-702, MaxExtenderPriority=10 scaled to MaxNodeScore).
"""

from __future__ import annotations

import json
import urllib.request
from typing import Dict, List, Optional, Tuple

from .api import types as api
from .utils import chaos

MAX_EXTENDER_PRIORITY = 10  # reference: extender/v1/types.go:109
DEFAULT_EXTENDER_TIMEOUT = 5.0


def _pod_doc(pod: api.Pod) -> Dict:
    return {
        "metadata": {"name": pod.metadata.name,
                     "namespace": pod.namespace,
                     "uid": pod.uid,
                     "labels": dict(pod.metadata.labels)},
        "spec": {"nodeName": pod.spec.node_name,
                 "schedulerName": pod.spec.scheduler_name,
                 "priority": pod.spec.priority},
    }


class ExtenderError(Exception):
    pass


class HTTPExtender:
    """reference: core/extender.go:42."""

    def __init__(self, config: Dict):
        self.url_prefix = config.get("urlPrefix", "").rstrip("/")
        self.filter_verb = config.get("filterVerb", "")
        self.prioritize_verb = config.get("prioritizeVerb", "")
        self.bind_verb = config.get("bindVerb", "")
        self.preempt_verb = config.get("preemptVerb", "")
        self.weight = config.get("weight", 1)
        self.timeout = config.get("httpTimeout", DEFAULT_EXTENDER_TIMEOUT)
        self.node_cache_capable = config.get("nodeCacheCapable", False)
        self.ignorable = config.get("ignorable", False)
        self.managed_resources = {r["name"] if isinstance(r, dict) else r
                                  for r in config.get("managedResources", [])}

    # -- wire ---------------------------------------------------------------

    def _send(self, verb: str, args: Dict) -> Dict:
        # reference: extender.go:412 send
        # chaos seam (utils/chaos.py "extender"): a transient webhook
        # transport error — flows through each verb's existing
        # ignorable/ExtenderError handling, never a new failure class
        chaos.raise_or_stall("extender")
        url = f"{self.url_prefix}/{verb}"
        data = json.dumps(args).encode()
        req = urllib.request.Request(
            url, data=data, headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            if resp.status != 200:
                raise ExtenderError(f"{url}: HTTP {resp.status}")
            return json.loads(resp.read().decode() or "{}")

    # -- verbs --------------------------------------------------------------

    def is_interested(self, pod: api.Pod) -> bool:
        """reference: extender.go:450 IsInterested — empty managedResources
        means every pod."""
        if not self.managed_resources:
            return True
        for c in pod.spec.containers + pod.spec.init_containers:
            for rl in (c.resources.requests, c.resources.limits):
                if any(name in self.managed_resources for name in rl):
                    return True
        return False

    def filter(self, pod: api.Pod,
               node_names: List[str]) -> Tuple[List[str], Dict[str, str]]:
        """Returns (feasible node names, failed nodes map)
        (reference: extender.go:273 Filter)."""
        if not self.filter_verb:
            return node_names, {}
        args = {"Pod": _pod_doc(pod), "NodeNames": node_names}
        try:
            result = self._send(self.filter_verb, args)
        except Exception as e:
            if self.ignorable:
                return node_names, {}
            raise ExtenderError(str(e))
        if result.get("Error"):
            raise ExtenderError(result["Error"])
        names = result.get("NodeNames")
        if names is None:
            names = node_names
        failed = result.get("FailedNodes") or {}
        return list(names), dict(failed)

    def prioritize(self, pod: api.Pod,
                   node_names: List[str]) -> Dict[str, float]:
        """Returns node -> weighted score contribution
        (reference: extender.go:343 Prioritize; weight application
        generic_scheduler.go:688)."""
        if not self.prioritize_verb:
            return {}
        args = {"Pod": _pod_doc(pod), "NodeNames": node_names}
        try:
            result = self._send(self.prioritize_verb, args)
        except Exception as e:
            if self.ignorable:
                return {}
            raise ExtenderError(str(e))
        out = {}
        for hp in result or []:
            out[hp["Host"]] = float(hp["Score"]) * self.weight
        return out

    def is_binder(self) -> bool:
        return bool(self.bind_verb)

    def bind(self, pod: api.Pod, node_name: str) -> None:
        """reference: extender.go:385 Bind."""
        args = {"PodName": pod.metadata.name,
                "PodNamespace": pod.namespace,
                "PodUID": pod.uid,
                "Node": node_name}
        result = self._send(self.bind_verb, args)
        if result.get("Error"):
            raise ExtenderError(result["Error"])

    def supports_preemption(self) -> bool:
        return bool(self.preempt_verb)

    def process_preemption(self, pod: api.Pod, node_victims: Dict):
        """reference: core/extender.go:317 ProcessPreemption — the extender
        may trim victims per node or drop nodes entirely; nodes absent from
        its result are no longer preemption candidates.  node_victims maps
        node name -> Victims (kubetpu.preemption)."""
        from .preemption import Victims
        args = {
            "pod": _pod_doc(pod),
            "nodeNameToMetaVictims": {
                name: {
                    "pods": [{"uid": p.uid} for p in v.pods],
                    "numPDBViolations": v.num_pdb_violations,
                } for name, v in node_victims.items()},
        }
        result = self._send(self.preempt_verb, args)
        by_uid = {p.uid: p
                  for v in node_victims.values() for p in v.pods}
        out = {}
        for name, meta in (result.get("nodeNameToMetaVictims") or {}).items():
            if name not in node_victims:
                continue  # never accept nodes we did not offer
            pods = [by_uid[m["uid"]] for m in (meta.get("pods") or [])
                    if m.get("uid") in by_uid]
            out[name] = Victims(
                pods=pods,
                num_pdb_violations=meta.get("numPDBViolations", 0))
        return out
