"""Cache debugger: dump + cache-vs-store drift comparison on SIGUSR2.

reference: pkg/scheduler/internal/cache/debugger/ — debugger.go:57
(ListenForSignal), comparer.go (CompareNodes/ComparePods against the
informer caches), dumper.go (cache + queue dump).  The drift comparer is
the reference's race detector for the assume/forget protocol; SURVEY.md §5
calls for keeping it host-side even though device snapshots are immutable.
"""

from __future__ import annotations

import logging
import signal
from typing import List, Tuple

LOG = logging.getLogger("kubetpu.debugger")


class CacheComparer:
    """reference: debugger/comparer.go."""

    def __init__(self, store, cache, queue):
        self.store = store
        self.cache = cache
        self.queue = queue

    def compare_nodes(self) -> Tuple[List[str], List[str]]:
        actual = {n.metadata.name for n in self.store.list("Node")}
        cached = {name for name, item in self.cache.nodes.items()
                  if item.info.node is not None}
        missed = sorted(actual - cached)
        redundant = sorted(cached - actual)
        return missed, redundant

    def compare_pods(self) -> Tuple[List[str], List[str]]:
        actual = {p.uid for p in self.store.list("Pod") if p.spec.node_name}
        cached = set(self.cache.pod_states)
        queued = {p.uid for p in self.queue.pending_pods()}
        missed = sorted(actual - cached - queued)
        redundant = sorted(cached - actual - set(self.cache.assumed_pods))
        return missed, redundant

    def compare(self) -> bool:
        """Returns True when cache and store agree; logs drift otherwise."""
        ok = True
        missed, redundant = self.compare_nodes()
        if missed or redundant:
            LOG.error("cache comparer: nodes missed %s redundant %s",
                      missed, redundant)
            ok = False
        missed, redundant = self.compare_pods()
        if missed or redundant:
            LOG.error("cache comparer: pods missed %s redundant %s",
                      missed, redundant)
            ok = False
        return ok


class CacheDumper:
    """reference: debugger/dumper.go."""

    def __init__(self, cache, queue):
        self.cache = cache
        self.queue = queue

    def dump(self) -> str:
        lines = ["Dump of cached NodeInfo:"]
        for name, item in self.cache.nodes.items():
            info = item.info
            lines.append(
                f'Node name: {name}; Requested: cpu={info.requested.milli_cpu}m '
                f'mem={info.requested.memory}; Pods: '
                f'{[p.pod.metadata.name for p in info.pods]}')
        lines.append("Dump of scheduling queue:")
        for p in self.queue.pending_pods():
            lines.append(f"  {p.namespace}/{p.metadata.name}")
        out = "\n".join(lines)
        LOG.info(out)
        return out


class CacheDebugger:
    """reference: debugger/debugger.go:57 — SIGUSR2 triggers dump+compare."""

    def __init__(self, store, cache, queue):
        self.comparer = CacheComparer(store, cache, queue)
        self.dumper = CacheDumper(cache, queue)

    def listen_for_signal(self) -> None:
        def handler(signum, frame):
            self.dumper.dump()
            self.comparer.compare()
        signal.signal(signal.SIGUSR2, handler)
