"""Incremental delta-tensorization: device-resident cluster state updated
by scatter, not rebuilt.

The flight recorder (PR 4) showed the serving host — not the device — is
the drain bottleneck: every non-chained cycle paid a full
``HostClusterArrays.build()`` walk over ALL nodes plus a fresh host→device
transfer, even when the cycle changed a handful of rows.  The
``DeltaTensorizer`` keeps ONE ``ClusterTensors`` alive on device across
cycles and, from the cache's commit/bind/evict/watch churn (per-node
``NodeInfo.generation`` bumps), emits compact ``[D]``-indexed update
tables (``state/tensors.py ClusterDelta``) applied by a donated, jit'd
scatter program (``models/programs.py apply_cluster_delta``,
``x.at[rows].set(..., mode="drop")`` so buffers update in place), bucketed
by ``pow2_bucket(D)`` to avoid recompiles.

The scheduler's gang-mode cycle CHAIN is the zero-delta special case of
this pipeline: the chain covers self-inflicted churn (the auction's own
placements, already materialized on device by ``materialize_assigned``),
while the DeltaTensorizer covers everything else — external binds, node
updates, evictions (including the preemption wave's victim deletions,
which reach it as ordinary cache churn and ride the same delta tables) —
and replaces the full rebuild as the chain-break recovery path.

Full rebuild remains the FALLBACK, demoted to an anti-entropy resync.
Triggers (each counted and reported through ``DeltaStats.reason``):

  * ``initial``             — no resident cluster yet
  * ``node-set``            — nodes added/removed/reordered (row ids move)
  * ``vocab-growth``        — an intern-table pow2 cap crossed (tensor
                              widths change), or the topokey vocab grew at
                              all (``topo_pair`` columns are filled at
                              build time from the key LIST, not the cap)
  * ``label-capacity``      — a node/pod outgrew the compact [., ML] id
                              lists
  * ``delta-too-large``     — dirty fraction above KUBETPU_DELTA_MAX_FRAC
                              (off by default)
  * ``anti-entropy``        — KUBETPU_RESYNC_INTERVAL delta cycles elapsed
  * ``pod-axis-growth``     — pod rows exhausted; the mirror pads to the
                              next pow2 bucket and re-uploads WITHOUT the
                              build() walk (the host-walk cost is the
                              bottleneck, not the transfer)

Term-carrying pod churn is NOT a resync trigger: the flattened
``ExistingTerms`` rebuild from the term OWNERS alone (``_refresh_terms``,
the ``delta-terms`` span) and replace wholesale — they are small, and a
1-in-5-pods-with-anti-affinity drain would otherwise resync every cycle.

Bit-exactness contract (tested by tests/test_delta.py): after any
sequence of deltas, the resident tensors match a from-scratch ``build()``
of the same NodeInfos against the same InternTable byte-for-byte, up to
the documented stable-row permutation of the existing-pod axis (a fresh
build packs pods in node-walk order; the delta path keeps rows stable and
reuses freed rows lowest-first).  Known deviation: when several nodes
report the SAME image with DIFFERENT sizes, build() keeps the last walked
node's size while the delta path keeps the last updated node's.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..utils import devstats as udevstats
from ..utils import journal as ujournal
from ..utils.intern import pow2_bucket
from ..utils.trace import wallclock
from .tensors import (ClusterDelta, HostClusterArrays, SnapshotBuilder,
                      clear_pod_row, fill_node_row, fill_pod_row,
                      gather_delta, pod_has_terms, vocab_signature)

RESYNC_INTERVAL_ENV = "KUBETPU_RESYNC_INTERVAL"
MAX_FRAC_ENV = "KUBETPU_DELTA_MAX_FRAC"
# anti-entropy VERIFIER cadence (delta cycles between device/mirror
# fingerprint checks); 0 = off, the default — a disarmed run performs
# zero extra readbacks (the chaos poison test enforces it)
VERIFY_INTERVAL_ENV = "KUBETPU_VERIFY_INTERVAL"
DEFAULT_RESYNC_INTERVAL = 512
# dirty-fraction fallback is OFF by default (1.0 = never): even a
# fully-dirty delta beats a rebuild — the refill walk is the same
# per-node work, but it skips the intern pass, the term rebuild, the
# fresh array allocation and most of the transfer.  Operators can lower
# it (KUBETPU_DELTA_MAX_FRAC=0.5) if a workload proves otherwise.
DEFAULT_MAX_FRAC = 1.0

# pod-axis mirror fields padded on growth (pad value per field)
_POD_FIELDS = (("_pod_kv_ids", -1), ("pod_key", False), ("pod_ns_hot", 0.0),
               ("pod_node", -1), ("pod_valid", False),
               ("pod_terminating", False))

# fields excluded from the anti-entropy fingerprint: the dense label
# one-hots exist ONLY on device (the mirror holds compact [., ML] id
# lists and to_device densifies — state/tensors.py), so there is no
# cheap host twin to sum against.  Their source id lists feed pod_key /
# keymask / topo_pair, which ARE fingerprinted, so label-scatter faults
# still surface; the documented blind spot is a corruption of the dense
# kv bits alone.
_FP_SKIP = ("kv", "pod_kv")


def _wrapsum_host(x: np.ndarray) -> int:
    """uint32 wrap-sum of a mirror array's element bits: bools count set
    bits, floats sum their f32 bit patterns, ints sum mod 2^32 — the
    exact integer twin of _wrapsum_dev (no float accumulation anywhere,
    so the comparison is bit-exact at any size)."""
    x = np.asarray(x)
    if x.dtype == np.bool_:
        v = x.astype(np.uint32)
    elif np.issubdtype(x.dtype, np.floating):
        v = np.ascontiguousarray(x.astype(np.float32)).view(np.uint32)
    else:
        v = x.astype(np.uint32)
    return int(v.sum(dtype=np.uint64) & 0xFFFFFFFF)


def _wrapsum_dev(x):
    """Device twin of _wrapsum_host: a [""] uint32 scalar, computed with
    EAGER ops (not a jit root — the verifier must not widen the census
    compile surface; it runs off the hot path on a cadence)."""
    import jax.numpy as jnp
    from jax import lax
    if x.dtype == jnp.bool_:
        v = x.astype(jnp.uint32)
    elif jnp.issubdtype(x.dtype, jnp.floating):
        v = lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    else:
        v = x.astype(jnp.uint32)
    return jnp.sum(v, dtype=jnp.uint32)


class DeltaStats(NamedTuple):
    """One refresh()'s outcome — the flight-recorder/bench feed."""
    delta_rows: int                 # node rows + pod rows actually updated
    resync: bool
    reason: str                     # "" on pure delta cycles
    spans: Tuple[Tuple[str, float, float], ...]  # (name, t0, t1)


class DeltaTensorizer:
    """Keeps ClusterTensors resident on device and updates them by
    bounded scatters from the cycle's cache churn.

    Owned by the serving thread (like the scheduler's chain); the host
    mirror (``HostClusterArrays``) is the source of truth the device
    tensors always equal, and a resync re-derives everything from the
    snapshot.  ``mesh`` keeps the resident cluster SHARDED so sharded
    profiles stop re-``device_put``-ing the whole [N, R] tensors — the
    replicated delta tables scatter into the local shards
    (parallel/mesh.py sharded_apply_cluster_delta).
    """

    def __init__(self, hard_pod_affinity_weight: int = 1, mesh=None,
                 profile: str = "",
                 resync_interval: Optional[int] = None,
                 max_delta_frac: Optional[float] = None,
                 verify_interval: Optional[int] = None):
        self.builder = SnapshotBuilder(
            hard_pod_affinity_weight=hard_pod_affinity_weight)
        self.hard_pod_affinity_weight = hard_pod_affinity_weight
        self.mesh = mesh
        self.profile = profile
        self.resync_interval = (resync_interval if resync_interval is not None
                                else int(os.environ.get(
                                    RESYNC_INTERVAL_ENV,
                                    str(DEFAULT_RESYNC_INTERVAL))))
        self.max_delta_frac = (max_delta_frac if max_delta_frac is not None
                               else float(os.environ.get(
                                   MAX_FRAC_ENV, str(DEFAULT_MAX_FRAC))))
        self.cluster = None                      # device ClusterTensors
        self.host: Optional[HostClusterArrays] = None
        self.node_names: List[str] = []          # row order
        self.node_gen: Dict[str, int] = {}
        self.node_pods: Dict[str, List[str]] = {}   # name -> uid list
        self.node_terms: Dict[str, bool] = {}    # name -> owns term pods
        self.pod_row: Dict[str, int] = {}        # uid -> row
        self.free_rows: List[int] = []           # kept sorted, pop lowest
        self.next_pod_row = 0
        self.caps = None                         # vocab signature
        self.cycles_since_resync = 0
        self.resync_count = 0
        # anti-entropy verifier (fingerprint_device vs fingerprint_host
        # every verify_interval delta cycles; 0 = off)
        self.verify_interval = (verify_interval
                                if verify_interval is not None
                                else int(os.environ.get(
                                    VERIFY_INTERVAL_ENV, "0")))
        self.cycles_since_verify = 0
        self.verify_count = 0
        self.divergence_count = 0
        # cycle-journal capture seam (utils/journal.py): when the journal
        # is armed, each refresh() stashes the exact input it applied to
        # the resident cluster — ("resync", pickled mirror) on any full
        # rebuild/re-upload, ("delta", pickled (ClusterDelta, terms)) on
        # a scatter cycle, ("noop", None) on zero-dirty cycles — and the
        # scheduler pops it into the cycle's journal record
        # (take_capture).  Disarmed this stays None: zero allocations.
        self.capture = None

    def take_capture(self):
        """Pop the last refresh()'s journal capture (None when the
        journal is disarmed — the seam costs one attribute read)."""
        cap, self.capture = self.capture, None
        return cap

    def _capture_resync(self) -> None:
        """Serialize the freshly-uploaded mirror as a journal anchor
        (armed only).  Pickled EAGERLY: later refreshes mutate the
        mirror arrays in place, so a lazy reference would record the
        wrong snapshot."""
        if ujournal.journal() is not None:
            self.capture = ("resync", pickle.dumps(self.host, protocol=4))

    # ------------------------------------------------------------- helpers

    def signature(self) -> tuple:
        """The tensor-width signature of the current vocab (shared with
        the scheduler's chain guard — state/tensors.vocab_signature)."""
        return vocab_signature(self.builder.table)

    def safe_to_donate(self, uncommitted_clusters) -> bool:
        """Donation gate for the depth-k pipelined drain: the donated
        scatter may only consume the resident buffers when NO
        dispatched-but-uncommitted cycle's cluster IS the resident —
        every in-flight ring slot's commit-side device work (preemption
        wave, decision audit) still dispatches against its cluster, and
        a donated buffer would be invalid by then.  Chained cycles hold
        their own materialized clusters and never block donation."""
        return not any(c is self.cluster for c in uncommitted_clusters)

    def pod_uid_list(self) -> List[Optional[str]]:
        """Row-ordered uid list sized to the pod-axis capacity (the
        scheduler's chain_pod_uids / CycleContext.pod_rows feed)."""
        if self.host is None:
            return []
        out: List[Optional[str]] = [None] * self.host.arrays[
            "pod_node"].shape[0]
        for uid, r in self.pod_row.items():
            out[r] = uid
        return out

    # ------------------------------------------------------- anti-entropy

    def fingerprint_device(self) -> np.ndarray:
        """[K] uint32 per-table wrap-sums of the DEVICE residents — one
        small readback (the eager per-leaf sums stack into one array and
        transfer together)."""
        import jax
        import jax.numpy as jnp
        vals = []
        for name in type(self.cluster)._fields:
            if name in _FP_SKIP:
                continue
            for leaf in jax.tree.leaves(getattr(self.cluster, name)):
                vals.append(_wrapsum_dev(leaf))
        return np.asarray(jnp.stack(vals))

    def fingerprint_host(self) -> np.ndarray:
        """The host mirror's twin of fingerprint_device, same leaf order
        (ClusterTensors field order; term pytrees flatten identically)."""
        import jax
        a = self.host.arrays
        vals = []
        for name in type(self.cluster)._fields:
            if name in _FP_SKIP:
                continue
            for leaf in jax.tree.leaves(a[name]):
                vals.append(_wrapsum_host(leaf))
        return np.asarray(vals, np.uint32)

    def verify(self) -> bool:
        """One anti-entropy check: True when the device residents match
        the host mirror bit-for-bit under the per-table fingerprint."""
        ok = bool(np.array_equal(self.fingerprint_device(),
                                 self.fingerprint_host()))
        self.verify_count += 1
        if not ok:
            self.divergence_count += 1
        return ok

    def _verify_tick(self, node_infos, names, pending):
        """Cadence gate around verify(): returns (spans, stats) where
        spans carries the verify span when a check ran and stats is the
        divergence-triggered resync's DeltaStats (reason
        "verify-divergence") or None when consistent / not due.  OFF
        (verify_interval == 0, the default) this is two attribute reads
        — no device work, no readback."""
        if not self.verify_interval or self.cluster is None:
            return (), None
        self.cycles_since_verify += 1
        if self.cycles_since_verify < self.verify_interval:
            return (), None
        self.cycles_since_verify = 0
        tv = wallclock()
        ok = self.verify()
        span = (("verify", tv, wallclock()),)
        if ok:
            return span, None
        # divergence: the mirror is the source of truth (refilled from
        # NodeInfos each cycle), so the targeted repair is the blessed
        # full resync — re-derives and re-uploads everything
        _cluster, stats = self._resync(node_infos, names,
                                       "verify-divergence", wallclock(),
                                       pending)
        return span, stats._replace(spans=span + stats.spans)

    # ------------------------------------------------------------- refresh

    def refresh(self, node_infos, pending=(), donate: bool = True):
        """Bring the resident cluster up to date with the snapshot's
        NodeInfos.  Returns (cluster, DeltaStats).  pending: PodInfos of
        this cycle's pending (and nominated) pods — interned HERE so the
        vocab-growth check always sees them (and so a compacting resync
        re-interns them into its fresh table).  donate=False keeps the
        previous device buffers alive (an in-flight pipelined cycle still
        reads them)."""
        t0 = wallclock()
        if pending:
            self.builder.intern_pending(pending)
        names = [ni.node_name for ni in node_infos]
        if self.cluster is None:
            return self._resync(node_infos, names, "initial", t0, pending)
        if names != self.node_names:
            return self._resync(node_infos, names, "node-set", t0, pending)
        # BEFORE the zero-dirty early return: pending/nominated pods can
        # grow the vocab with zero node churn, and serving the resident
        # tensors then would hand the program stale widths (or an all- -1
        # topo_pair column for a brand-new topology key)
        if self.signature() != self.caps:
            return self._resync(node_infos, names, "vocab-growth", t0,
                                pending)
        if self.cycles_since_resync >= self.resync_interval:
            return self._resync(node_infos, names, "anti-entropy", t0,
                                pending)
        dirty = [(i, ni) for i, ni in enumerate(node_infos)
                 if ni.generation != self.node_gen.get(ni.node_name)]
        if not dirty:
            self.cycles_since_resync += 1
            if ujournal.journal() is not None:
                # zero-dirty: the journal records "previous cluster, as
                # is" (a verify-divergence resync below overwrites this)
                self.capture = ("noop", None)
            # the verifier ticks on zero-dirty cycles too: a corruption
            # injected by the LAST scatter must not hide behind a quiet
            # cluster until the next churn
            vspan, vstats = self._verify_tick(node_infos, names, pending)
            if vstats is not None:
                return self.cluster, vstats
            return self.cluster, DeltaStats(0, False, "", vspan)
        if len(dirty) > self.max_delta_frac * max(len(names), 1):
            return self._resync(node_infos, names, "delta-too-large", t0,
                                pending)
        # term-carrying pod churn does NOT force a full resync: the
        # flattened ExistingTerms rebuild from the term OWNERS alone (a
        # small subset) and replace wholesale — see _refresh_terms
        hw = self.hard_pod_affinity_weight
        terms_dirty = any(
            self.node_terms.get(ni.node_name)
            or any(pod_has_terms(pi, hw) for pi in ni.pods)
            for _, ni in dirty)
        # intern BEFORE the width check so new strings from dirty nodes
        # count against the caps the resident tensors were sized with
        self.builder._intern_node_strings([ni for _, ni in dirty])
        if self.signature() != self.caps:
            return self._resync(node_infos, names, "vocab-growth", t0,
                                pending)
        a = self.host.arrays
        MLn = a["_kv_ids"].shape[1]
        MLp = a["_pod_kv_ids"].shape[1]
        for _, ni in dirty:
            if len(ni.node.metadata.labels) + 1 > MLn:
                return self._resync(node_infos, names, "label-capacity",
                                    t0, pending)
            for pi in ni.pods:
                if len(pi.pod.metadata.labels) > MLp:
                    return self._resync(node_infos, names,
                                        "label-capacity", t0, pending)

        # ---- pod-row churn: free EVERY departed row across all dirty
        # nodes BEFORE scanning for additions — a same-uid pod moving
        # from a higher- to a lower-indexed dirty node would otherwise be
        # skipped by the add scan (stale mapping still present) and then
        # popped by the later free, leaving the refill with no row
        touched_pods: set = set()
        adds: List[Tuple[object, int]] = []    # (PodInfo, node row)
        for _, ni in dirty:
            old = self.node_pods.get(ni.node_name, [])
            new_set = {pi.pod.uid for pi in ni.pods}
            for uid in old:
                if uid not in new_set:
                    row = self.pod_row.pop(uid)
                    clear_pod_row(a, row)
                    touched_pods.add(row)
                    self.free_rows.append(row)
        for i, ni in dirty:
            for pi in ni.pods:
                if pi.pod.uid not in self.pod_row:
                    adds.append((pi, i))
        self.free_rows.sort()
        PP = a["pod_node"].shape[0]
        need = len(adds) - len(self.free_rows)
        grown = False
        if need > 0 and self.next_pod_row + need > PP:
            self._grow_pod_axis(self.next_pod_row + need)
            grown = True
            PP = a["pod_node"].shape[0]
        for pi, n_idx in adds:
            row = (self.free_rows.pop(0) if self.free_rows
                   else self.next_pod_row)
            if row == self.next_pod_row:
                self.next_pod_row += 1
            self.pod_row[pi.pod.uid] = row

        # ---- refill the mirror rows (node + every pod on a dirty node —
        # covers in-place pod updates without per-pod generations)
        t = self.builder.table
        # a dirty node can have interned a NEW taint inside the cap: the
        # [T] vocab-metadata rows for fresh ids must land too (build()
        # fills them from the vocab; ids are append-only, so only the
        # tail can be stale)
        from ..api import types as api
        for ti in range(len(t.taint)):
            if not a["taint_is_hard"][ti] and not a["taint_is_prefer"][ti]:
                _, _, effect = t.taint.key(ti)
                a["taint_is_hard"][ti] = effect in (
                    api.TAINT_EFFECT_NO_SCHEDULE,
                    api.TAINT_EFFECT_NO_EXECUTE)
                a["taint_is_prefer"][ti] = (
                    effect == api.TAINT_EFFECT_PREFER_NO_SCHEDULE)
        image_nodes = a["_image_nodes"]
        node_rows = []
        for i, ni in dirty:
            old_imgs = set(np.nonzero(a["images"][i])[0].tolist())
            fill_node_row(a, i, ni, t)
            new_imgs = set(np.nonzero(a["images"][i])[0].tolist())
            for ii in old_imgs - new_imgs:
                image_nodes[ii] -= 1
            for ii in new_imgs - old_imgs:
                image_nodes[ii] += 1
            for pi in ni.pods:
                row = self.pod_row[pi.pod.uid]
                fill_pod_row(a, row, pi, i, t)
                touched_pods.add(row)
            self.node_pods[ni.node_name] = [pi.pod.uid for pi in ni.pods]
            self.node_terms[ni.node_name] = any(pod_has_terms(pi, hw)
                                                for pi in ni.pods)
            self.node_gen[ni.node_name] = ni.generation
            node_rows.append(i)
        # images that no node carries anymore read 0 in a fresh build
        a["image_size"][image_nodes <= 0] = 0.0
        a["image_spread"] = image_nodes / max(float(len(node_infos)), 1.0)

        term_span = ()
        if terms_dirty:
            t_terms = wallclock()
            self._refresh_terms(node_infos)
            term_span = (("delta-terms", t_terms, wallclock()),)

        pod_rows = sorted(touched_pods)
        if grown:
            # the pod axis changed shape: scatter can't grow a buffer, so
            # re-upload the (already-updated) mirror — no build() walk
            self.cycles_since_resync = 0
            self.resync_count += 1
            t_build = wallclock()
            self._upload()
            self._capture_resync()
            return self.cluster, DeltaStats(
                len(node_rows) + len(pod_rows), True, "pod-axis-growth",
                (("delta-build", t0, t_build),) + term_span
                + (("resync", t_build, wallclock()),))
        delta = gather_delta(self.host, node_rows, pod_rows)
        t_build = wallclock()
        self.cluster = self._apply(delta, donate=donate,
                                   replace_terms=terms_dirty)
        if terms_dirty:
            # wholesale term replacement can change the term-table
            # shapes — the only delta-path event that moves residency
            self._register_residency()
        self.cycles_since_resync += 1
        spans = ((("delta-build", t0, t_build),) + term_span
                 + (("delta-apply", t_build, wallclock()),))
        vspan, vstats = self._verify_tick(node_infos, names, pending)
        if vstats is not None:
            return self.cluster, vstats._replace(spans=spans
                                                 + vstats.spans)
        return self.cluster, DeltaStats(
            len(node_rows) + len(pod_rows), False, "", spans + vspan)

    # ------------------------------------------------------------- resync

    def _resync(self, node_infos, names: List[str], reason: str,
                t0: float, pending=()):
        """The blessed full rebuild: anti-entropy resync + every fallback
        trigger.  Also the vocab COMPACTION point: everything re-derives
        here, so intern ids are free to move and the table restarts FRESH
        — without this, dead label values (pod-template-hash churn across
        rollouts) would grow the vocab, and so the resident tensor
        widths, without bound.  Ids only need stability BETWEEN resyncs
        (the delta path's contract).  pending: this cycle's pending/
        nominated PodInfos, re-interned into the fresh table before
        sizing so batch tensors and cluster tensors agree on widths."""
        self.builder = SnapshotBuilder(
            hard_pod_affinity_weight=self.hard_pod_affinity_weight)
        if pending:
            self.builder.intern_pending(pending)
        host = self.builder.build(node_infos)
        a = host.arrays
        self.host = host
        self.node_names = list(names)
        self.node_gen = {ni.node_name: ni.generation for ni in node_infos}
        self.node_pods = {ni.node_name: [pi.pod.uid for pi in ni.pods]
                          for ni in node_infos}
        hw = self.hard_pod_affinity_weight
        self.node_terms = {ni.node_name: any(pod_has_terms(pi, hw)
                                             for pi in ni.pods)
                           for ni in node_infos}
        self.pod_row = dict(a["_pod_rows"])
        self.next_pod_row = len(self.pod_row)
        self.free_rows = []
        self.caps = self.signature()
        self.cycles_since_resync = 0
        # a resync re-uploads the mirror wholesale, so device == mirror
        # by construction; restart the verify cadence
        self.cycles_since_verify = 0
        self.resync_count += 1
        self._upload()
        self._capture_resync()
        return self.cluster, DeltaStats(
            0, True, reason, (("resync", t0, wallclock()),))

    def _grow_pod_axis(self, needed: int) -> None:
        """Pad the mirror's pod-axis arrays to the next pow2 bucket —
        freed-row reuse keeps rows stable, so growth only appends
        padding rows identical to a fresh build's."""
        a = self.host.arrays
        PP = a["pod_node"].shape[0]
        new_pp = pow2_bucket(needed, 8)
        n = new_pp - PP
        if n <= 0:
            return
        for field, fill in _POD_FIELDS:
            arr = a[field]
            pad = np.full((n,) + arr.shape[1:], fill, arr.dtype)
            a[field] = np.concatenate([arr, pad])

    def _upload(self) -> None:
        """Full host→device transfer of the mirror (resync / pod-axis
        growth); sharded when a mesh is configured so the resident
        tensors live pre-sharded across cycles."""
        cluster = self.host.to_device()
        if self.mesh is not None:
            from ..parallel import mesh as pmesh
            cluster = pmesh.shard_cluster(cluster, self.mesh)
        self.cluster = cluster
        self._register_residency()

    def _register_residency(self) -> None:
        """Residency-ledger seam (utils/devstats.py): register the
        resident cluster's per-table bytes under this profile — the
        shape walk happens only when residency can have CHANGED (resync,
        pod-axis growth, wholesale term replacement; scatters keep
        shapes).  Disarmed: one attribute read."""
        if udevstats.devstats() is None or self.cluster is None:
            return
        udevstats.register_cluster(
            "delta-resident", self.profile or "default", self.cluster,
            len(self.node_names), meta={"resyncs": self.resync_count})

    def _refresh_terms(self, node_infos) -> None:
        """Term-only rebuild: walk the term OWNERS (a small subset of the
        existing pods), recompile the flattened ExistingTerms against the
        persistent table, and stage them in the mirror for wholesale
        replacement — the owner collection follows the same node-walk
        order as build(), so row content matches a rebuild exactly (term
        pod_idx points at the stable delta rows).  This demotes
        "topology-term structural change" from a full-resync trigger to a
        bounded partial rebuild."""
        hw = self.hard_pod_affinity_weight
        filter_owners, score_owners = [], []
        for ni in node_infos:
            for pi in ni.pods:
                row = self.pod_row[pi.pod.uid]
                if pi.required_anti_affinity_terms:
                    filter_owners.append((pi, row))
                if (pi.preferred_affinity_terms
                        or pi.preferred_anti_affinity_terms
                        or pi.required_affinity_terms):
                    score_owners.append((pi, row))
        a = self.host.arrays
        a["filter_terms"] = self.builder._build_terms(filter_owners,
                                                      kind="filter")
        a["score_terms"] = self.builder._build_terms(score_owners,
                                                     kind="score")

    def _device_terms(self):
        """The mirror's term tensors as device (mesh: replicated) arrays —
        terms replace wholesale, no scatter needed."""
        import jax
        import jax.numpy as jnp
        a = self.host.arrays
        # jnp.array, not asarray: these leaves join the DONATED cluster
        # (see HostClusterArrays.to_device) — an aliased mirror buffer
        # would be clobbered by the scatter's buffer reuse
        ft = jax.tree.map(jnp.array, a["filter_terms"])
        st = jax.tree.map(jnp.array, a["score_terms"])
        if self.mesh is not None:
            from ..parallel import mesh as pmesh
            ft = pmesh.replicate(ft, self.mesh)
            st = pmesh.replicate(st, self.mesh)
        return ft, st

    def _apply(self, delta: ClusterDelta, donate: bool,
               replace_terms: bool = False):
        from ..models import programs
        from ..utils import chaos
        cluster = self.cluster
        if replace_terms:
            # swap the term pytrees BEFORE the jit call: the scatter
            # program passes terms through untouched, and a donated
            # pass-through of the OLD terms would invalidate buffers the
            # new cluster no longer uses anyway
            ft, st = self._device_terms()
            cluster = cluster._replace(filter_terms=ft, score_terms=st)
        if ujournal.journal() is not None:
            # journal capture: the exact scatter tables (and wholesale
            # term replacement) this cycle applies — pickled eagerly, the
            # mirror the term pytrees alias mutates in place next cycle.
            # Captured BEFORE the chaos seam below: the journal records
            # applied INTENT, so a chaos-dropped scatter replays as a
            # detectable divergence (the fault class the replay rig
            # exists to expose)
            a = self.host.arrays
            terms = ((a["filter_terms"], a["score_terms"])
                     if replace_terms else None)
            self.capture = ("delta", pickle.dumps((delta, terms),
                                                  protocol=4))
        # chaos seam (utils/chaos.py "delta"): "drop" loses the scatter
        # entirely (the mirror was already refilled, so device and host
        # now silently diverge — the exact fault class the anti-entropy
        # verifier exists to catch); "corrupt" applies the scatter, then
        # flips one resident value the way a bad DMA would
        act = chaos.action("delta")
        if act == "drop":
            return cluster
        if self.mesh is not None:
            from ..parallel import mesh as pmesh
            new = pmesh.sharded_apply_cluster_delta(
                cluster, delta, self.mesh, donate=donate)
        else:
            new = programs.apply_cluster_delta(cluster, delta,
                                               donate=donate)
        if act == "corrupt":
            new = new._replace(requested=new.requested.at[0, 0].add(1.0))
        ds = udevstats.devstats()
        if ds is not None and ds.deep_active():
            # deep-timing micro-fence (utils/devstats.py): on the
            # sampled cycles, measure the scatter's actual device time —
            # normally it completes invisibly behind the auction's
            # dispatch.  Completion is observed by reading back ONE
            # small output ([N] node_valid — a single executable's
            # outputs complete together), not block_until_ready, which
            # the axon tunnel does not block.  Waiting changes no value
            # (armed-vs-disarmed parity golden); the overhead is
            # counted in fence_wait_s
            t_f = time.perf_counter()
            np.asarray(new.node_valid)
            ds.record_program("apply_cluster_delta",
                              time.perf_counter() - t_f, source="fence",
                              in_bytes=udevstats.pytree_nbytes(delta))
        return new
