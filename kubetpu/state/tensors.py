"""Cluster snapshot tensorization: NodeInfos -> dense device arrays.

This is the TPU-native analog of the reference's scheduler cache snapshot
(reference: pkg/scheduler/internal/cache/snapshot.go:29 Snapshot,
cache.go:202 UpdateSnapshot): instead of a list of NodeInfo pointers handed
to 16 goroutines, the cluster becomes a struct-of-arrays over the node axis
(plus an existing-pods axis for affinity/spread) that one jitted program
consumes.  All strings are interned (kubetpu/utils/intern.py); all set
membership is multi-hot.

Unit conventions (chosen so every value the scheduler compares is exact in
f32 — see kubetpu/api/resource.py):
  channel 0: CPU millicores          (raw int value)
  channel 1: memory MiB              (bytes / 2^20; exact for Mi-granular values)
  channel 2: ephemeral-storage MiB
  channel 3: pod count / max pods
  channel 4+: scalar (extended) resources, raw integer value, one channel
              per interned resource name.
"""

from __future__ import annotations

import functools
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..api import types as api
from ..api.resource import Resource
from ..framework.types import NodeInfo, PodInfo
from ..ops.selectors import FIELD_PREFIX, SelectorCompiler, SelectorSet
from ..utils.intern import InternTable, pow2_bucket

MIB = float(2 ** 20)

# fixed channels
CH_CPU, CH_MEM, CH_EPH, CH_PODS = 0, 1, 2, 3
N_FIXED_CHANNELS = 4

# taint effect codes
EFFECT_CODES = {api.TAINT_EFFECT_NO_SCHEDULE: 0,
                api.TAINT_EFFECT_PREFER_NO_SCHEDULE: 1,
                api.TAINT_EFFECT_NO_EXECUTE: 2}


def resource_to_channels(r: Resource, table: InternTable, R: int,
                         intern_new: bool = True) -> np.ndarray:
    out = np.zeros((R,), np.float32)
    out[CH_CPU] = r.milli_cpu
    out[CH_MEM] = r.memory / MIB
    out[CH_EPH] = r.ephemeral_storage / MIB
    out[CH_PODS] = r.allowed_pod_number
    for name, v in r.scalar_resources.items():
        i = table.rname.intern(name) if intern_new else table.rname.get(name)
        ch = N_FIXED_CHANNELS + i
        if 0 <= i and ch < R:
            out[ch] = v
    return out


class ExistingTerms(NamedTuple):
    """Flattened (anti-)affinity terms owned by *existing* pods, matched
    against incoming pods.  Two instances live in ClusterTensors: one for
    filtering (required anti-affinity of existing pods, reference:
    interpodaffinity/filtering.go:166 getExistingAntiAffinityCounts) and one
    for scoring (preferred +w / -w and required-affinity x hardWeight,
    reference: interpodaffinity/scoring.go:128 processExistingPod)."""
    sel: SelectorSet           # [Et] selectors over incoming-pod labels
    ns_hot: jnp.ndarray        # [Et, NS] f32 — namespaces the term applies to
    topo_key: jnp.ndarray      # [Et] i32 index into topokey axis
    pod_idx: jnp.ndarray       # [Et] i32 owning existing-pod row
    weight: jnp.ndarray        # [Et] f32 (signed; 1.0 for filter terms)
    valid: jnp.ndarray         # [Et] bool


class ClusterTensors(NamedTuple):
    """One immutable device-side cluster snapshot (a JAX pytree)."""
    # node axis ------------------------------------------------------------
    allocatable: jnp.ndarray        # [N, R] f32
    requested: jnp.ndarray          # [N, R] f32
    nonzero_requested: jnp.ndarray  # [N, 2] f32 (cpu milli, mem MiB)
    node_valid: jnp.ndarray         # [N] bool
    unschedulable: jnp.ndarray      # [N] bool (.spec.unschedulable)
    kv: jnp.ndarray                 # [N, L] bool — node has label (k,v)
    keymask: jnp.ndarray            # [N, K] bool — node has label key
    num: jnp.ndarray                # [N, K] f32 — numeric label value (+inf
                                    # when absent/non-numeric: keeps cluster
                                    # tensors NaN-free so the sanitizer's
                                    # jax_debug_nans pass stays meaningful;
                                    # selectors guard with isfinite)
    topo_pair: jnp.ndarray          # [N, TK] i32 — kv id of (topokey, value), -1 absent
    taints: jnp.ndarray             # [N, T] bool
    ports: jnp.ndarray              # [N, P] bool
    images: jnp.ndarray             # [N, I] bool
    avoid_hot: jnp.ndarray          # [N, AV] bool — node's preferAvoidPods entries
                                    #   over the (controller kind, uid) vocab
    zone_hot: jnp.ndarray           # [N, Z] f32 one-hot over the ZONE vocab
                                    #   (Z = pow2 zone-count bucket, NOT N:
                                    #   zone aggregation must stay a tiny
                                    #   [., Z] matmul — an [N, N] one-hot
                                    #   made DefaultPodTopologySpread's
                                    #   normalize the single most expensive
                                    #   op at 8k nodes)
    # vocab-side metadata ---------------------------------------------------
    taint_is_hard: jnp.ndarray      # [T] bool (NoSchedule | NoExecute)
    taint_is_prefer: jnp.ndarray    # [T] bool (PreferNoSchedule)
    image_size: jnp.ndarray         # [I] f32 bytes
    image_spread: jnp.ndarray       # [I] f32 fraction of nodes having the image
    # existing pods axis ----------------------------------------------------
    pod_kv: jnp.ndarray             # [P, L] bool
    pod_key: jnp.ndarray            # [P, K] bool
    pod_ns_hot: jnp.ndarray         # [P, NS] f32 one-hot
    pod_node: jnp.ndarray           # [P] i32 node row (-1 invalid)
    pod_valid: jnp.ndarray          # [P] bool
    pod_terminating: jnp.ndarray    # [P] bool (deletionTimestamp set)
    # existing pods' terms --------------------------------------------------
    filter_terms: ExistingTerms     # required anti-affinity (filter)
    score_terms: ExistingTerms      # preferred +/-, required x hardWeight (score)

    @property
    def n_nodes_cap(self) -> int:
        return self.allocatable.shape[0]


class HostClusterArrays(NamedTuple):
    """Numpy twin of ClusterTensors (what the builder maintains).

    The two label one-hots (kv [N, L], pod_kv [P, L]) are held COMPACT as
    [., ML] i32 id lists and densified on device at to_device time: at 8k
    nodes L is ~16k (hostname values), so the dense bools are ~134 MB each
    while the id lists are ~0.5 MB — and the tunnel uploads at ~35 MB/s,
    which made a fresh-world upload the single slowest device event
    (~8 s, the r4 verdict's unexplained cycle_p99 outlier)."""
    arrays: dict

    def to_device(self) -> ClusterTensors:
        a = self.arrays
        L = a["_kv_cap"]
        vals = [None if f in ("kv", "pod_kv") else a[f]
                for f in ClusterTensors._fields]
        # jnp.array, NOT jnp.asarray: asarray zero-copies a 64-byte-
        # aligned numpy buffer on CPU, and the delta scatter DONATES the
        # cluster (programs.apply_cluster_delta) — XLA then reuses the
        # aliased buffer for unrelated outputs and silently corrupts the
        # HOST MIRROR these arrays belong to.  Small mirrors only align
        # by malloc luck (flaky); production-sized ones are page-aligned
        # (always).  Caught by the anti-entropy verifier's false-positive
        # divergences; the copy is paid once per resync.
        dev = jax.tree.map(lambda x: x if x is None else jnp.array(x),
                           ClusterTensors(*vals),
                           is_leaf=lambda x: x is None)
        return dev._replace(kv=_densify_ids(jnp.asarray(a["_kv_ids"]), L),
                            pod_kv=_densify_ids(jnp.asarray(a["_pod_kv_ids"]),
                                                L))


@functools.partial(jax.jit, static_argnames=("L",))
def _densify_ids(ids, L: int):
    """[X, ML] i32 id lists (-1 pad) -> [X, L] bool multi-hot, on device."""
    X = ids.shape[0]
    rows = jnp.arange(X)[:, None]
    return jnp.zeros((X, L), bool).at[
        rows, jnp.clip(ids, 0, L - 1)].max((ids >= 0) & (ids < L))


# Well-known topology keys are always present so zone/hostname spreading
# needs no vocab growth (reference: pkg/apis/core/v1/well_known_labels.go).
SEED_TOPOKEYS = (api.LABEL_HOSTNAME, api.LABEL_ZONE, api.LABEL_REGION,
                 api.LABEL_ZONE_LEGACY, api.LABEL_REGION_LEGACY)


class SnapshotBuilder:
    """Builds HostClusterArrays from a list of NodeInfos.

    Mirrors the roles of snapshot.go:49 (NewSnapshot) — including the
    HavePodsWithAffinityList secondary index, which here becomes the
    flattened ExistingTerms tensors.  DefaultHardPodAffinityWeight = 1
    (reference: apis/config/v1beta1/defaults.go hardPodAffinityWeight).
    """

    def __init__(self, table: Optional[InternTable] = None,
                 hard_pod_affinity_weight: int = 1):
        self.table = table or InternTable()
        for k in SEED_TOPOKEYS:
            self.table.topokey.intern(k)
        self.hard_pod_affinity_weight = hard_pod_affinity_weight
        self.compiler = SelectorCompiler(self.table)

    # -- interning helpers --------------------------------------------------

    def _intern_node_strings(self, nodes: List[NodeInfo]) -> None:
        """First pass: make sure vocab contains everything in the cluster so
        bucket caps are final before array allocation."""
        t = self.table
        for ni in nodes:
            node = ni.node
            if node is None:
                continue
            for k, v in node.metadata.labels.items():
                t.kv.intern((k, v)); t.key.intern(k)
            t.kv.intern((FIELD_PREFIX + "metadata.name", node.name))
            t.key.intern(FIELD_PREFIX + "metadata.name")
            for taint in node.spec.taints:
                t.taint.intern((taint.key, taint.value, taint.effect))
            for name in ni.image_states:
                t.image.intern(_norm_image(name))
            for r in ni.allocatable.scalar_resources:
                t.rname.intern(r)
            zk = zone_key(node)
            if zk:
                t.zone.intern(zk)
            for kind, uid in _avoid_entries(node):
                t.avoid.intern((kind, uid))
            for triple in ni.used_ports:
                for pid in _port_ids_node(triple):
                    t.port.intern(pid)
            for pi in ni.pods:
                p = pi.pod
                t.ns.intern(p.namespace)
                for k, v in p.metadata.labels.items():
                    t.kv.intern((k, v)); t.key.intern(k)
                for term in (pi.required_anti_affinity_terms
                             + [w.term for w in pi.preferred_affinity_terms]
                             + [w.term for w in pi.preferred_anti_affinity_terms]
                             + pi.required_affinity_terms):
                    t.topokey.intern(term.topology_key)
                    for ns in term.namespaces:
                        t.ns.intern(ns)

    def intern_pending(self, pods: List[PodInfo]) -> None:
        """Pre-intern the strings of *pending* pods so vocab capacities are
        final before snapshot arrays are sized.  Without this, two batch pods
        sharing a label or hostPort that exists nowhere else in the cluster
        could not see each other in the intra-batch (scan) interactions."""
        t = self.table
        for pi in pods:
            p = pi.pod
            t.ns.intern(p.namespace)
            for k, v in p.metadata.labels.items():
                t.kv.intern((k, v)); t.key.intern(k)
            for c in p.spec.containers:
                for port in c.ports:
                    if port.host_port <= 0:
                        continue
                    triple = (port.protocol or "TCP", port.host_ip or "0.0.0.0",
                              port.host_port)
                    for pid in _port_ids_node(triple) + port_ids_pod(triple):
                        t.port.intern(pid)
            for term in (pi.required_affinity_terms + pi.required_anti_affinity_terms
                         + [w.term for w in pi.preferred_affinity_terms]
                         + [w.term for w in pi.preferred_anti_affinity_terms]):
                t.topokey.intern(term.topology_key)
                for ns in term.namespaces:
                    t.ns.intern(ns)
            for c in p.spec.topology_spread_constraints:
                t.topokey.intern(c.topology_key)

    # -- build --------------------------------------------------------------

    def build(self, nodes: List[NodeInfo]) -> HostClusterArrays:
        self._intern_node_strings(nodes)
        t = self.table
        N = pow2_bucket(len(nodes), 8)
        R = N_FIXED_CHANNELS + t.rname.cap
        L, K, TK = t.kv.cap, t.key.cap, t.topokey.cap
        T, P, I, NS = t.taint.cap, t.port.cap, t.image.cap, t.ns.cap
        AV = t.avoid.cap
        n_pods = sum(len(ni.pods) for ni in nodes)
        PP = pow2_bucket(n_pods, 8)
        # compact label-id forms of kv/pod_kv (densified on device)
        MLn = pow2_bucket(max((len(ni.node.metadata.labels) + 1
                               for ni in nodes if ni.node is not None),
                              default=1), 4)
        MLp = pow2_bucket(max((len(pi.pod.metadata.labels)
                               for ni in nodes for pi in ni.pods),
                              default=1), 4)

        d: dict = {
            "allocatable": np.zeros((N, R), np.float32),
            "requested": np.zeros((N, R), np.float32),
            "nonzero_requested": np.zeros((N, 2), np.float32),
            "node_valid": np.zeros((N,), bool),
            "unschedulable": np.zeros((N,), bool),
            "_kv_ids": np.full((N, MLn), -1, np.int32),
            "_pod_kv_ids": np.full((PP, MLp), -1, np.int32),
            "_kv_cap": L,
            "keymask": np.zeros((N, K), bool),
            "num": np.full((N, K), np.inf, np.float32),
            "topo_pair": np.full((N, TK), -1, np.int32),
            "taints": np.zeros((N, T), bool),
            "ports": np.zeros((N, P), bool),
            "images": np.zeros((N, I), bool),
            "avoid_hot": np.zeros((N, AV), bool),
            "zone_hot": np.zeros((N, t.zone.cap), np.float32),
            "taint_is_hard": np.zeros((T,), bool),
            "taint_is_prefer": np.zeros((T,), bool),
            "image_size": np.zeros((I,), np.float32),
            "image_spread": np.zeros((I,), np.float32),
            "pod_key": np.zeros((PP, K), bool),
            "pod_ns_hot": np.zeros((PP, NS), np.float32),
            "pod_node": np.full((PP,), -1, np.int32),
            "pod_valid": np.zeros((PP,), bool),
            "pod_terminating": np.zeros((PP,), bool),
        }

        # vocab metadata
        for i in range(len(t.taint)):
            _, _, effect = t.taint.key(i)
            d["taint_is_hard"][i] = effect in (api.TAINT_EFFECT_NO_SCHEDULE,
                                               api.TAINT_EFFECT_NO_EXECUTE)
            d["taint_is_prefer"][i] = effect == api.TAINT_EFFECT_PREFER_NO_SCHEDULE

        image_nodes = np.zeros((I,), np.float32)
        pod_row = 0
        pod_rows: Dict[str, int] = {}  # pod uid -> row
        filter_owners: List[Tuple[PodInfo, int]] = []
        score_owners: List[Tuple[PodInfo, int]] = []

        for n_idx, ni in enumerate(nodes):
            node = ni.node
            if node is None:
                continue
            fill_node_row(d, n_idx, ni, t)
            for ii in np.nonzero(d["images"][n_idx])[0]:
                image_nodes[ii] += 1

            for pi in ni.pods:
                fill_pod_row(d, pod_row, pi, n_idx, t)
                pod_rows[pi.pod.uid] = pod_row
                if pi.required_anti_affinity_terms:
                    filter_owners.append((pi, pod_row))
                if (pi.preferred_affinity_terms or pi.preferred_anti_affinity_terms
                        or pi.required_affinity_terms):
                    score_owners.append((pi, pod_row))
                pod_row += 1

        n_valid = max(float(len(nodes)), 1.0)
        d["image_spread"] = image_nodes / n_valid

        d["filter_terms"] = self._build_terms(filter_owners, kind="filter")
        d["score_terms"] = self._build_terms(score_owners, kind="score")
        # delta-maintenance metadata (state/delta.py DeltaTensorizer):
        # stable row assignments + per-image node counts, so incremental
        # updates can start exactly where this build left off
        d["_pod_rows"] = pod_rows
        d["_image_nodes"] = image_nodes
        return HostClusterArrays(arrays=d)

    def _build_terms(self, owners: List[Tuple[PodInfo, int]], kind: str) -> ExistingTerms:
        t = self.table
        NS = t.ns.cap
        sels, nss, topos, pods, weights = [], [], [], [], []

        def add(term, pod_row, weight):
            sels.append(term.selector)
            nss.append(term.namespaces)
            topos.append(t.topokey.get(term.topology_key))
            pods.append(pod_row)
            weights.append(float(weight))

        for pi, row in owners:
            if kind == "filter":
                for term in pi.required_anti_affinity_terms:
                    add(term, row, 1.0)
            else:
                for w in pi.preferred_affinity_terms:
                    add(w.term, row, w.weight)
                for w in pi.preferred_anti_affinity_terms:
                    add(w.term, row, -w.weight)
                if self.hard_pod_affinity_weight:
                    for term in pi.required_affinity_terms:
                        add(term, row, self.hard_pod_affinity_weight)

        Et = pow2_bucket(len(sels), 1)
        sel_set = self.compiler.compile(sels + [None] * (Et - len(sels)), pad_s=Et)
        ns_hot = np.zeros((Et, NS), np.float32)
        topo_key = np.zeros((Et,), np.int32)
        pod_idx = np.zeros((Et,), np.int32)
        weight = np.zeros((Et,), np.float32)
        valid = np.zeros((Et,), bool)
        for i in range(len(sels)):
            for ns in nss[i]:
                j = t.ns.get(ns)
                if j >= 0:
                    ns_hot[i, j] = 1.0
            topo_key[i] = max(topos[i], 0)
            pod_idx[i] = pods[i]
            weight[i] = weights[i]
            valid[i] = True
        return ExistingTerms(sel=sel_set, ns_hot=ns_hot, topo_key=topo_key,
                             pod_idx=pod_idx, weight=weight, valid=valid)


# --------------------------------------------------------------------------
# Per-row fills, shared by SnapshotBuilder.build (the from-scratch walk) and
# state/delta.py DeltaTensorizer (the incremental path).  Bit-exactness
# contract: filling a row through these helpers produces byte-identical
# arrays to a fresh build of the same NodeInfo against the same InternTable,
# so delta-maintained tensors never drift from a rebuild.


def fill_node_row(d: dict, n_idx: int, ni: NodeInfo, t: InternTable) -> None:
    """(Re)fill every node-axis array row for one NodeInfo.  Clears the row
    first so refilling a previously-populated row (the delta path) leaves
    no stale label/taint/port bits behind."""
    node = ni.node
    R = d["allocatable"].shape[1]
    d["node_valid"][n_idx] = True
    d["unschedulable"][n_idx] = node.spec.unschedulable
    d["_kv_ids"][n_idx] = -1
    d["keymask"][n_idx] = False
    d["num"][n_idx] = np.inf
    d["topo_pair"][n_idx] = -1
    d["taints"][n_idx] = False
    d["ports"][n_idx] = False
    d["images"][n_idx] = False
    d["avoid_hot"][n_idx] = False
    d["zone_hot"][n_idx] = 0.0
    d["allocatable"][n_idx] = resource_to_channels(ni.allocatable, t, R)
    req = resource_to_channels(ni.requested, t, R)
    req[CH_PODS] = len(ni.pods)
    d["requested"][n_idx] = req
    d["nonzero_requested"][n_idx, 0] = ni.non_zero_requested.milli_cpu
    d["nonzero_requested"][n_idx, 1] = ni.non_zero_requested.memory / MIB
    labels = dict(node.metadata.labels)
    labels[FIELD_PREFIX + "metadata.name"] = node.name
    for li, (k, v) in enumerate(labels.items()):
        d["_kv_ids"][n_idx, li] = t.kv.get((k, v))
        ki = t.key.get(k)
        d["keymask"][n_idx, ki] = True
        try:
            d["num"][n_idx, ki] = float(int(v))
        except ValueError:
            pass
    for tk_i in range(len(t.topokey)):
        tk = t.topokey.key(tk_i)
        if tk in labels:
            d["topo_pair"][n_idx, tk_i] = t.kv.get((tk, labels[tk]))
    for taint in node.spec.taints:
        d["taints"][n_idx, t.taint.get((taint.key, taint.value,
                                        taint.effect))] = True
    for triple in ni.used_ports:
        for pid in _port_ids_node(triple):
            d["ports"][n_idx, t.port.get(pid)] = True
    for name, size in ni.image_states.items():
        ii = t.image.get(_norm_image(name))
        d["images"][n_idx, ii] = True
        d["image_size"][ii] = size
    for kind, uid in _avoid_entries(node):
        d["avoid_hot"][n_idx, t.avoid.get((kind, uid))] = True
    zk = zone_key(node)
    if zk:
        d["zone_hot"][n_idx, t.zone.get(zk)] = 1.0


def fill_pod_row(d: dict, row: int, pi: PodInfo, n_idx: int,
                 t: InternTable) -> None:
    """(Re)fill one existing-pod row.  Clears first (delta row reuse)."""
    clear_pod_row(d, row)
    p = pi.pod
    d["pod_node"][row] = n_idx
    d["pod_valid"][row] = True
    d["pod_terminating"][row] = p.metadata.deletion_timestamp is not None
    d["pod_ns_hot"][row, t.ns.get(p.namespace)] = 1.0
    for li, (k, v) in enumerate(p.metadata.labels.items()):
        d["_pod_kv_ids"][row, li] = t.kv.get((k, v))
        d["pod_key"][row, t.key.get(k)] = True


def clear_pod_row(d: dict, row: int) -> None:
    """Reset a pod row to build-time defaults (an evicted pod's freed row
    must be byte-identical to a fresh build's padding row)."""
    d["pod_node"][row] = -1
    d["pod_valid"][row] = False
    d["pod_terminating"][row] = False
    d["pod_ns_hot"][row] = 0.0
    d["_pod_kv_ids"][row] = -1
    d["pod_key"][row] = False


def vocab_signature(table: InternTable) -> tuple:
    """Every width the cluster tensors are sized with: each vocab's pow2
    cap (zone included) plus the topokey LENGTH — ``topo_pair`` columns
    are filled from the key LIST at build time, so topokey growth inside
    the cap still invalidates built tensors.  The ONE signature both
    resident-state guards compare (the scheduler's gang chain and the
    DeltaTensorizer): a vocab added here invalidates both, never one."""
    caps = tuple((n, getattr(table, n).cap) for n in
                 ("kv", "key", "ns", "topokey", "rname", "port", "taint",
                  "image", "avoid", "zone"))
    return caps + (("topokey_len", len(table.topokey)),)


def pod_has_terms(pi: PodInfo, hard_pod_affinity_weight: int = 1) -> bool:
    """True when this existing pod contributes rows to filter_terms or
    score_terms — the delta path resyncs when such a pod churns, because
    the flattened term tensors are only rebuilt on a full build()."""
    return bool(pi.required_anti_affinity_terms
                or pi.preferred_affinity_terms
                or pi.preferred_anti_affinity_terms
                or (hard_pod_affinity_weight and pi.required_affinity_terms))


class ClusterDelta(NamedTuple):
    """Compact [D]-indexed update tables for one cycle's dirty rows,
    applied on device by models/programs.py apply_cluster_delta
    (``x.at[rows].set(..., mode="drop")``).  Row vectors are padded to a
    pow2 bucket with ONE-PAST-CAPACITY indices (N for node rows, P for pod
    rows): "drop" mode discards out-of-bounds scatters, while a -1 pad
    would WRAP to the last row and corrupt it.  Label one-hots ride as
    compact id lists ([D, ML] i32) and densify on device, mirroring the
    HostClusterArrays transfer contract.  The two [I] image vectors are
    cluster-global (spread is a fraction of all nodes) and tiny, so every
    delta replaces them wholesale."""
    node_rows: np.ndarray          # [Dn] i32 (pad = N: dropped)
    allocatable: np.ndarray        # [Dn, R] f32
    requested: np.ndarray          # [Dn, R] f32
    nonzero_requested: np.ndarray  # [Dn, 2] f32
    node_valid: np.ndarray         # [Dn] bool
    unschedulable: np.ndarray      # [Dn] bool
    kv_ids: np.ndarray             # [Dn, MLn] i32 (densified on device)
    keymask: np.ndarray            # [Dn, K] bool
    num: np.ndarray                # [Dn, K] f32
    topo_pair: np.ndarray          # [Dn, TK] i32
    taints: np.ndarray             # [Dn, T] bool
    ports: np.ndarray              # [Dn, P] bool
    images: np.ndarray             # [Dn, I] bool
    avoid_hot: np.ndarray          # [Dn, AV] bool
    zone_hot: np.ndarray           # [Dn, Z] f32
    image_size: np.ndarray         # [I] f32 (full replace)
    image_spread: np.ndarray       # [I] f32 (full replace)
    taint_is_hard: np.ndarray      # [T] bool (full replace: a dirty node
                                   # can intern a NEW taint inside the cap)
    taint_is_prefer: np.ndarray    # [T] bool (full replace)
    pod_rows: np.ndarray           # [Dp] i32 (pad = P: dropped)
    pod_kv_ids: np.ndarray         # [Dp, MLp] i32 (densified on device)
    pod_key: np.ndarray            # [Dp, K] bool
    pod_ns_hot: np.ndarray         # [Dp, NS] f32
    pod_node: np.ndarray           # [Dp] i32
    pod_valid: np.ndarray          # [Dp] bool
    pod_terminating: np.ndarray    # [Dp] bool


def gather_delta(host: HostClusterArrays, node_rows: List[int],
                 pod_rows: List[int]) -> ClusterDelta:
    """Slice the dirty rows out of the host mirror into pow2-bucketed
    update tables (the host half of the delta pipeline)."""
    a = host.arrays
    N = a["allocatable"].shape[0]
    PP = a["pod_node"].shape[0]
    Dn = pow2_bucket(len(node_rows), 8)
    Dp = pow2_bucket(len(pod_rows), 8)
    nr = np.full((Dn,), N, np.int32)
    nr[:len(node_rows)] = node_rows
    pr = np.full((Dp,), PP, np.int32)
    pr[:len(pod_rows)] = pod_rows

    def g(field: str, rows: List[int], cap: int) -> np.ndarray:
        arr = a[field]
        out = np.zeros((cap,) + arr.shape[1:], arr.dtype)
        if rows:
            out[:len(rows)] = arr[rows]
        return out

    return ClusterDelta(
        node_rows=nr,
        allocatable=g("allocatable", node_rows, Dn),
        requested=g("requested", node_rows, Dn),
        nonzero_requested=g("nonzero_requested", node_rows, Dn),
        node_valid=g("node_valid", node_rows, Dn),
        unschedulable=g("unschedulable", node_rows, Dn),
        kv_ids=g("_kv_ids", node_rows, Dn),
        keymask=g("keymask", node_rows, Dn),
        num=g("num", node_rows, Dn),
        topo_pair=g("topo_pair", node_rows, Dn),
        taints=g("taints", node_rows, Dn),
        ports=g("ports", node_rows, Dn),
        images=g("images", node_rows, Dn),
        avoid_hot=g("avoid_hot", node_rows, Dn),
        zone_hot=g("zone_hot", node_rows, Dn),
        image_size=a["image_size"].copy(),
        image_spread=np.asarray(a["image_spread"], np.float32).copy(),
        taint_is_hard=a["taint_is_hard"].copy(),
        taint_is_prefer=a["taint_is_prefer"].copy(),
        pod_rows=pr,
        pod_kv_ids=g("_pod_kv_ids", pod_rows, Dp),
        pod_key=g("pod_key", pod_rows, Dp),
        pod_ns_hot=g("pod_ns_hot", pod_rows, Dp),
        pod_node=g("pod_node", pod_rows, Dp),
        pod_valid=g("pod_valid", pod_rows, Dp),
        pod_terminating=g("pod_terminating", pod_rows, Dp))


def _norm_image(name: str) -> str:
    """Normalize image name: bare names get :latest; a registry-less repo is
    left as-is (reference: imagelocality/image_locality.go normalizedImageName)."""
    if "@" in name:
        return name
    tag_sep = name.rfind(":")
    slash = name.rfind("/")
    if tag_sep <= slash:  # no tag after last path component
        return name + ":latest"
    return name


WILDCARD_IP = "0.0.0.0"
_ANY = "__any__"
_WILD = "__wild__"


def _port_ids_node(triple: Tuple[str, str, int]):
    """Port ids a *node* registers for one used (proto, ip, port).

    Encodes HostPortInfo's wildcard semantics
    (reference: framework/v1alpha1/types.go:694 HostPortInfo.CheckConflict)
    as set-intersection: specific ip registers {specific, ANY}; wildcard
    registers {WILD, ANY}.  A pod checks {specific, WILD} (specific ip) or
    {ANY} (wildcard).  Intersection != 0  <=>  CheckConflict == true.
    """
    proto, ip, port = triple
    if ip == WILDCARD_IP:
        return [(proto, _WILD, port), (proto, _ANY, port)]
    return [(proto, ip, port), (proto, _ANY, port)]


def port_ids_pod(triple: Tuple[str, str, int]):
    """Port ids a *pod* probes for one wanted (proto, ip, port)."""
    proto, ip, port = triple
    if ip == WILDCARD_IP:
        return [(proto, _ANY, port)]
    return [(proto, ip, port), (proto, _WILD, port)]


def _avoid_entries(node: api.Node) -> List[Tuple[str, str]]:
    """(kind, uid) pairs from the preferAvoidPods annotation (reference:
    pkg/apis/core/v1/helper/helpers.go:239 GetAvoidPodsFromNodeAnnotations,
    matched by kind+UID in nodepreferavoidpods/node_prefer_avoid_pods.go:76)."""
    raw = node.metadata.annotations.get(api.PREFER_AVOID_PODS_ANNOTATION_KEY)
    if not raw:
        return []
    import json
    out = []
    try:
        doc = json.loads(raw)
        for entry in doc.get("preferAvoidPods", []):
            ctrl = entry.get("podSignature", {}).get("podController", {})
            out.append((ctrl.get("kind", ""), ctrl.get("uid", "")))
    except (ValueError, AttributeError):
        return []
    return out


def zone_key(node: api.Node) -> str:
    """region:zone key for zone-aware spreading
    (reference: pkg/util/node/node.go:148 GetZoneKey)."""
    labels = node.metadata.labels
    # legacy failure-domain labels take precedence (reference behavior)
    region = labels.get(api.LABEL_REGION_LEGACY, labels.get(api.LABEL_REGION, ""))
    zone = labels.get(api.LABEL_ZONE_LEGACY, labels.get(api.LABEL_ZONE, ""))
    if not region and not zone:
        return ""
    return region + ":\x00:" + zone
