"""Scheduler cache: assume/forget protocol + incremental snapshots.

reference: pkg/scheduler/internal/cache/cache.go (schedulerCache :58,
AssumePod :338, FinishBinding :359, ForgetPod :383, AddPod :416,
UpdatePod :452, RemovePod :481, AddNode :514, UpdateSnapshot :202,
cleanupAssumedPods :704) and interface.go (the Cache contract).

The cache optimistically holds "assumed" pods — placed by the scheduler but
not yet confirmed bound by a watch event — with a TTL after binding
finishes (30 s default, reference: scheduler.go:227 durationToExpireAssumedPod).
Every NodeInfo mutation bumps its Generation; UpdateSnapshot copies only
NodeInfos whose generation is newer than the snapshot's, keeping snapshot
cost proportional to churn, not cluster size.  A doubly-linked list keeps
recently-updated nodes at the head so the generation scan can stop early
(reference: cache.go:64 headNode / moveNodeInfoToHead).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..api import types as api
from ..framework.types import NodeInfo, next_generation
from .node_tree import NodeTree

DEFAULT_ASSUME_TTL = 30.0  # reference: scheduler.go:56,227


@dataclass
class _PodState:
    pod: api.Pod
    deadline: Optional[float] = None      # set by FinishBinding
    binding_finished: bool = False


class _NodeItem:
    """Doubly-linked NodeInfo wrapper (reference: cache.go:46 nodeInfoListItem)."""
    __slots__ = ("info", "next", "prev")

    def __init__(self, info: NodeInfo):
        self.info = info
        self.next: Optional["_NodeItem"] = None
        self.prev: Optional["_NodeItem"] = None


class Snapshot:
    """Immutable-by-convention per-cycle view (reference:
    internal/cache/snapshot.go:29 Snapshot)."""

    def __init__(self):
        self.node_info_map: Dict[str, NodeInfo] = {}
        self.node_info_list: List[NodeInfo] = []
        self.have_pods_with_affinity_list: List[NodeInfo] = []
        self.generation = 0

    def num_nodes(self) -> int:
        return len(self.node_info_list)

    def get(self, name: str) -> Optional[NodeInfo]:
        return self.node_info_map.get(name)


class SchedulerCache:
    def __init__(self, ttl: float = DEFAULT_ASSUME_TTL,
                 clock=time.time, cleanup_period: float = 1.0,
                 expire_listener=None):
        # expire_listener(pod): called whenever an assumed pod is dropped
        # by TTL expiry (the lost-watch-event path) so owners of derived
        # state (the scheduler's chained tensors) can invalidate it
        self.expire_listener = expire_listener
        self._ttl = ttl
        self._clock = clock
        self._lock = threading.RLock()
        self.nodes: Dict[str, _NodeItem] = {}  # kubelint: guarded-by(_lock)
        self.head: Optional[_NodeItem] = None  # kubelint: guarded-by(_lock)
        self.node_tree = NodeTree()  # kubelint: guarded-by(_lock)
        self.assumed_pods: Dict[str, bool] = {}      # uid -> true  # kubelint: guarded-by(_lock)
        self.pod_states: Dict[str, _PodState] = {}   # uid -> state  # kubelint: guarded-by(_lock)
        self._stop = threading.Event()
        self._cleanup_period = cleanup_period
        self._thread: Optional[threading.Thread] = None

    # -- linked list --------------------------------------------------------

    def _move_to_head(self, item: _NodeItem) -> None:
        # reference: cache.go:145 moveNodeInfoToHead
        if item is self.head:
            return
        if item.prev is not None:
            item.prev.next = item.next
        if item.next is not None:
            item.next.prev = item.prev
        if self.head is not None:
            self.head.prev = item
        item.next = self.head
        item.prev = None
        self.head = item

    def _remove_from_list(self, item: _NodeItem) -> None:
        # reference: cache.go:166 removeNodeInfoFromList
        if item.prev is not None:
            item.prev.next = item.next
        if item.next is not None:
            item.next.prev = item.prev
        if item is self.head:
            self.head = item.next

    def _node_item(self, name: str) -> _NodeItem:
        item = self.nodes.get(name)
        if item is None:
            item = _NodeItem(NodeInfo())
            self.nodes[name] = item
        return item

    def node_info(self, name: str) -> Optional[NodeInfo]:
        """A CLONE of the live NodeInfo for a node — includes assumed pods,
        unlike the cycle snapshot (reference: cache.go GetNodeInfo).  Cloned
        under the lock so callers never race informer-thread mutations."""
        with self._lock:
            item = self.nodes.get(name)
            return item.info.clone() if item is not None else None

    def node_fit_view(self, name: str):
        """(allocatable, requested, pod count) copies for a cheap live fit
        check — O(Resource) per call instead of a full NodeInfo clone."""
        with self._lock:
            item = self.nodes.get(name)
            if item is None:
                return None
            info = item.info
            return (info.allocatable.clone(), info.requested.clone(),
                    len(info.pods))

    # -- pods ---------------------------------------------------------------

    def assume_pod(self, pod: api.Pod, pinfo=None) -> None:
        """reference: cache.go:338 AssumePod.  pinfo: optional pre-parsed
        PodInfo wrapping this pod (hot-path callers avoid a re-parse)."""
        with self._lock:
            if pod.uid in self.pod_states:
                raise ValueError(f"pod {pod.uid} is in the cache, "
                                 "so can't be assumed")
            self._add_pod(pod, pinfo)
            self.pod_states[pod.uid] = _PodState(pod=pod)
            self.assumed_pods[pod.uid] = True

    def finish_binding(self, pod: api.Pod, now: Optional[float] = None) -> None:
        """reference: cache.go:359 FinishBinding — starts the expiry TTL."""
        with self._lock:
            st = self.pod_states.get(pod.uid)
            if st is not None and self.assumed_pods.get(pod.uid):
                st.binding_finished = True
                st.deadline = (now if now is not None else self._clock()) + self._ttl

    def forget_pod(self, pod: api.Pod) -> None:
        """reference: cache.go:383 ForgetPod."""
        with self._lock:
            st = self.pod_states.get(pod.uid)
            if st is not None and st.pod.spec.node_name != pod.spec.node_name:
                raise ValueError(f"pod {pod.uid} was assumed on "
                                 f"{st.pod.spec.node_name} but assigned to "
                                 f"{pod.spec.node_name}")
            if not self.assumed_pods.get(pod.uid):
                raise ValueError(f"pod {pod.uid} wasn't assumed, "
                                 "so can't be forgotten")
            self._remove_pod(st.pod)
            del self.pod_states[pod.uid]
            del self.assumed_pods[pod.uid]

    def add_pod(self, pod: api.Pod) -> None:
        """Watch-confirmed pod (reference: cache.go:416 AddPod)."""
        with self._lock:
            st = self.pod_states.get(pod.uid)
            if st is not None and self.assumed_pods.get(pod.uid):
                if st.pod.spec.node_name != pod.spec.node_name:
                    # the pod was added to a different node than assumed
                    self._remove_pod(st.pod)
                    self._add_pod(pod)
                self.assumed_pods.pop(pod.uid, None)
                st.deadline = None
                st.pod = pod
            elif st is None:
                self._add_pod(pod)
                self.pod_states[pod.uid] = _PodState(pod=pod)
            else:
                raise ValueError(f"pod {pod.uid} was already in added state")

    def update_pod(self, old: api.Pod, new: api.Pod) -> None:
        """reference: cache.go:452 UpdatePod."""
        with self._lock:
            st = self.pod_states.get(old.uid)
            if st is None:
                raise ValueError(f"pod {old.uid} is not added to cache")
            if self.assumed_pods.get(old.uid):
                raise ValueError(f"assumed pod {old.uid} should not be updated")
            self._remove_pod(st.pod)
            self._add_pod(new)
            st.pod = new

    def remove_pod(self, pod: api.Pod) -> None:
        """reference: cache.go:481 RemovePod."""
        with self._lock:
            st = self.pod_states.get(pod.uid)
            if st is None:
                raise ValueError(f"pod {pod.uid} is not found in cache")
            self._remove_pod(st.pod)
            del self.pod_states[pod.uid]
            self.assumed_pods.pop(pod.uid, None)

    def get_pod(self, pod: api.Pod) -> Optional[api.Pod]:
        with self._lock:
            st = self.pod_states.get(pod.uid)
            return st.pod if st else None

    def is_assumed_pod(self, pod: api.Pod) -> bool:
        with self._lock:
            return bool(self.assumed_pods.get(pod.uid))

    def _add_pod(self, pod: api.Pod, pinfo=None) -> None:
        item = self._node_item(pod.spec.node_name)
        item.info.add_pod(pod, pinfo)
        self._move_to_head(item)

    def _remove_pod(self, pod: api.Pod) -> None:
        item = self.nodes.get(pod.spec.node_name)
        if item is None:
            return
        item.info.remove_pod(pod)
        if item.info.node is None and not item.info.pods:
            # placeholder created by a pod on an unknown node
            self._remove_from_list(item)
            del self.nodes[pod.spec.node_name]
        else:
            self._move_to_head(item)

    # -- nodes --------------------------------------------------------------

    def add_node(self, node: api.Node) -> None:
        """reference: cache.go:514 AddNode."""
        with self._lock:
            item = self._node_item(node.name)
            self.node_tree.add_node(node)
            item.info.set_node(node)
            self._move_to_head(item)

    def update_node(self, old: api.Node, new: api.Node) -> None:
        with self._lock:
            item = self._node_item(new.name)
            self.node_tree.update_node(old, new)
            item.info.set_node(new)
            self._move_to_head(item)

    def remove_node(self, node: api.Node) -> None:
        """reference: cache.go:552 RemoveNode — NodeInfo stays if pods are
        still attached (they may be deleted later)."""
        with self._lock:
            item = self.nodes.get(node.name)
            if item is None:
                raise ValueError(f"node {node.name} is not found")
            item.info.node = None
            item.info.generation = next_generation()
            if not item.info.pods:
                self._remove_from_list(item)
                del self.nodes[node.name]
            else:
                self._move_to_head(item)
            self.node_tree.remove_node(node)

    def node_count(self) -> int:
        with self._lock:
            return len(self.nodes)

    def pod_count(self) -> int:
        with self._lock:
            return sum(len(i.info.pods) for i in self.nodes.values())

    # -- snapshot -----------------------------------------------------------

    def update_snapshot(self, snapshot: Snapshot) -> None:
        """Incremental snapshot refresh (reference: cache.go:202
        UpdateSnapshot): walk the recently-updated list head-first, copy
        NodeInfos newer than the snapshot generation, rebuild the ordered
        list only when nodes were added/removed or affinity pods changed."""
        with self._lock:
            balanced_gen = snapshot.generation
            update_all = False
            item = self.head
            while item is not None:
                info = item.info
                if info.generation <= balanced_gen:
                    break  # everything older is already in the snapshot
                if info.node is not None:
                    existing = snapshot.node_info_map.get(info.node_name)
                    if existing is None:
                        update_all = True
                    elif bool(existing.pods_with_affinity) != bool(
                            info.pods_with_affinity):
                        update_all = True
                    snapshot.node_info_map[info.node_name] = info.clone()
                item = item.next
            if self.head is not None:
                snapshot.generation = self.head.info.generation
            # removed nodes may still be in the snapshot map — compare
            # against the tree (reference compares nodeTree.numNodes,
            # cache.go:236: ghost NodeInfos with lingering pods don't count)
            if len(snapshot.node_info_map) > self.node_tree.num_nodes:
                live = {n for n, it in self.nodes.items()
                        if it.info.node is not None}
                for name in list(snapshot.node_info_map):
                    if name not in live:
                        del snapshot.node_info_map[name]
                update_all = True
            if update_all or len(snapshot.node_info_list) != len(
                    [i for i in self.nodes.values() if i.info.node is not None]):
                self._rebuild_snapshot_list(snapshot)
            else:
                # refresh affinity sublist from (possibly re-cloned) infos
                snapshot.node_info_list = [
                    snapshot.node_info_map[ni.node_name]
                    for ni in snapshot.node_info_list
                    if ni.node_name in snapshot.node_info_map]
                snapshot.have_pods_with_affinity_list = [
                    ni for ni in snapshot.node_info_list
                    if ni.pods_with_affinity]

    def _rebuild_snapshot_list(self, snapshot: Snapshot) -> None:
        # reference: cache.go:280 updateNodeInfoSnapshotList (zone order)
        snapshot.node_info_list = []
        snapshot.have_pods_with_affinity_list = []
        for name in self.node_tree.list():
            ni = snapshot.node_info_map.get(name)
            if ni is None:
                continue
            snapshot.node_info_list.append(ni)
            if ni.pods_with_affinity:
                snapshot.have_pods_with_affinity_list.append(ni)

    # -- assumed-pod expiry -------------------------------------------------

    def cleanup_assumed_pods(self, now: Optional[float] = None) -> None:
        """reference: cache.go:704 cleanupAssumedPods."""
        now = now if now is not None else self._clock()
        with self._lock:
            for uid in list(self.assumed_pods):
                st = self.pod_states[uid]
                if not st.binding_finished:
                    continue
                if st.deadline is not None and now >= st.deadline:
                    self._expire_pod(uid, st)

    def _expire_pod(self, uid: str, st: _PodState) -> None:
        self._remove_pod(st.pod)
        del self.pod_states[uid]
        del self.assumed_pods[uid]
        if self.expire_listener is not None:
            # the scheduler's chained tensors may still carry this ghost
            # pod's usage — let the owner invalidate them
            self.expire_listener(st.pod)

    def run(self) -> None:
        """Start the periodic expiry loop (reference: cache.go:696 run)."""
        def loop():
            while not self._stop.wait(self._cleanup_period):
                self.cleanup_assumed_pods()
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def close(self) -> None:
        """Idempotent: stops and joins the cleanup thread (it sleeps on the
        stop event, so it exits within one wait tick)."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)
        self._thread = None

    # -- debugging ----------------------------------------------------------

    def dump(self) -> Dict[str, object]:
        """reference: internal/cache/debugger/dumper.go."""
        with self._lock:
            return {
                "nodes": {n: {"pods": [p.pod.metadata.name
                                       for p in it.info.pods],
                              "generation": it.info.generation}
                          for n, it in self.nodes.items()},
                "assumed_pods": list(self.assumed_pods),
            }
