"""Device-side volume filter family: the [B, N] feasibility mask for
VolumeBinding(Filter) / VolumeZone / NodeVolumeLimits / {EBS,GCEPD,
AzureDisk,Cinder}Limits, computed in ONE jitted program per cycle.

The host plugin classes (kubetpu/plugins/volumes.py) remain the source of
truth for semantics — this module calls THEIR counting/limit-resolution
methods at tensorize time, then evaluates the per-node verdicts as
matmuls, replacing the O(B x N) Python filter loop that made PVC-heavy
batches at >=1000 nodes cost ~20M plugin calls per cycle (VERDICT r4
weak #6).  The host plugins still run at commit time (the scheduler's
commit-phase re-check) so intra-batch volume races keep the serial
guarantees.

Semantics covered (reference files per plugin docstrings):
- VolumeBinding.filter: bound PVC -> PV node-affinity match
  (volumebinding/volume_binding.go FindPodVolumes); unbound PVC ->
  matchable unbound PV on the node, or a WaitForFirstConsumer class
  (provisionable).  "Matchable" pre-filters by the claim's FULL
  requirement signature at overlay-build time — StorageClass, storage
  request vs PV capacity, access-mode superset (pv_satisfies_claim, the
  host plugin's own matcher) — host-side per distinct (class, size,
  modes) triple, so the device verdict agrees with the commit-time host
  re-check and PVC-heavy pipelined drains stop discarding speculative
  chains on capacity/mode mismatches.  Known deviation: claim label
  SELECTORS (spec.selector) are not matched (neither here nor in the
  host plugin), and immediate-binding unbound claims are still judged
  per node rather than failing the pod outright.
- VolumeZone: a node with NO zone/region labels passes; otherwise every
  bound PV's zone-ish label value set must contain the node's value
  (volumezone/volume_zone.go:80).
- Limits family: |used-distinct-vols(node, driver) U new(pod, driver)|
  <= resolved limit, checked only for drivers the pod demands
  (nodevolumelimits/{csi,non_csi}.go).
"""

from __future__ import annotations

import functools
from typing import Dict, List, NamedTuple, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..api import types as api
from ..ops.selectors import SelectorCompiler, SelectorSet, match_selectors
from ..utils.intern import pow2_bucket
from ..plugins import volumes as vplug

# host filter plugins this mask covers: pods whose only relevant host
# filters are these skip the per-(pod, node) Python loop entirely
DEVICE_COVERED_PLUGINS = frozenset({
    "VolumeBinding", "VolumeZone", "NodeVolumeLimits", "EBSLimits",
    "GCEPDLimits", "AzureDiskLimits", "CinderLimits", "VolumeRestrictions",
})


def _conflict_tokens(v: api.Volume):
    """(probe, register) conflict-token sets for VolumeRestrictions
    (volume_restrictions.go:48 isVolumeConflict), the NodePorts wildcard
    encoding: conflict(v, ev) <=> probe(v) & register(ev) != {}.
    GCE/ISCSI/RBD conflict unless BOTH are read-only; EBS always."""
    for kind, src, ro_exempt in (("gce", v.gce_persistent_disk, True),
                                 ("ebs", v.aws_elastic_block_store, False),
                                 ("iscsi", v.iscsi, True),
                                 ("rbd", v.rbd, True)):
        if not src:
            continue
        vid = (kind, src)
        if not ro_exempt:
            return [(vid, "any")], [(vid, "any")]
        if v.read_only:
            # conflicts only with a read-write holder
            return [(vid, "rw")], [(vid, "any")]
        return [(vid, "any"), (vid, "rw")], [(vid, "any"), (vid, "rw")]
    return [], []

_BIG = np.float32(2 ** 30)


class VolumeOverlay(NamedTuple):
    """Per-cycle host-built arrays for the device volume mask.  All string
    ids (volume ids, PV names, StorageClass names, limit drivers) use
    cycle-local vocabularies — nothing is interned globally."""
    # limits: vol vocab V (driver-qualified distinct volume ids)
    pod_vol_ids: np.ndarray    # [B, MV] i32 vol ids the pod demands (-1 pad)
    node_vol_ids: np.ndarray   # [N, MU] i32 vol ids in use on the node
    driver_hot: np.ndarray     # [V, D] f32 one-hot: vol id -> driver
    node_limit: np.ndarray     # [N, D] f32 resolved limit (BIG = none)
    # VolumeRestrictions conflict tokens (ports-style wildcard encoding)
    pod_conf_ids: np.ndarray   # [B, MC] i32 tokens the pod probes
    node_conf_ids: np.ndarray  # [N, MC2] i32 tokens registered on the node
    # VolumeBinding: bound-PV node affinity + unbound-PVC availability
    pv_sel: SelectorSet        # [PVT] per-(pv, term) node selectors
    pv_term_of: np.ndarray     # [PVT] i32 owning PV row (-1 pad)
    pv_no_aff: np.ndarray      # [PVu] bool PV has no nodeAffinity (always ok)
    pod_pv_hot: np.ndarray     # [B, PVu] f32 bound PVs the pod requires
    sc_pv_hot: np.ndarray      # [SC, PVu] f32 unbound PVs per StorageClass
    pod_sc_hot: np.ndarray     # [B, SC] f32 classes the pod needs available
    # VolumeZone
    zone_sel: SelectorSet      # [B] combined zone-label requirements
    pod_has_zone: np.ndarray   # [B] bool pod carries zone constraints
    pod_zone_err: np.ndarray   # [B] bool VolumeZone errors (unbound claim
                               #   without WFFC class / missing PV) — fails
                               #   only nodes that HAVE zone labels (the
                               #   no-zone-labels early pass wins first,
                               #   volume_zone.go:86)
    zone_keyids: np.ndarray    # [ZK] i32 key-vocab ids of the zone keys
    # hard per-pod failures (errors the host plugin turns into statuses)
    pod_all_fail: np.ndarray   # [B] bool


def _limit_plugins(store, enabled: Set[str]):
    out = []
    for cls in (vplug.EBSLimits, vplug.GCEPDLimits, vplug.AzureDiskLimits,
                vplug.CinderLimits):
        if cls.NAME in enabled:
            out.append((cls.NAME, cls(store)))
    return out


def build_volume_overlay(store, node_infos, pods: List[api.Pod], table,
                         enabled: Set[str]) -> Optional[VolumeOverlay]:
    """Build the overlay for a batch, or None when no pod needs it.
    `enabled`: names of the profile's enabled host filter plugins."""
    if store is None:
        return None
    relevant = [bool(p.spec.volumes) for p in pods]
    if not any(relevant):
        return None
    B = pow2_bucket(len(pods), 8)
    N = pow2_bucket(len(node_infos), 8)

    csi = vplug.NodeVolumeLimits(store) \
        if "NodeVolumeLimits" in enabled else None
    intree = _limit_plugins(store, enabled)
    binding = vplug.VolumeBinding(store) if "VolumeBinding" in enabled else None
    zone = vplug.VolumeZone(store) if "VolumeZone" in enabled else None
    restrict = "VolumeRestrictions" in enabled

    # ---- VolumeRestrictions conflict tokens
    conf_ids: Dict[Tuple, int] = {}

    def conf_tokens(pod, register: bool) -> List[int]:
        out: List[int] = []
        if not restrict:
            return out
        for v in pod.spec.volumes:
            probe, reg = _conflict_tokens(v)
            for tok in (reg if register else probe):
                out.append(conf_ids.setdefault(tok, len(conf_ids)))
        return out

    pod_conf_lists = [conf_tokens(p, register=False) if r else []
                      for p, r in zip(pods, relevant)]

    # ---- cycle-local vocabularies
    vol_ids: Dict[Tuple[str, str], int] = {}   # (driver, vol) -> id
    drivers: Dict[str, int] = {}               # driver key -> column

    def vol_id(driver: str, vol: str) -> int:
        d = drivers.setdefault(driver, len(drivers))
        return vol_ids.setdefault((driver, vol), len(vol_ids)), d

    def pod_demands(pod) -> List[int]:
        ids = []
        if csi is not None:
            by_drv: Dict[str, Set[str]] = {}
            csi._count_csi(pod, by_drv)
            for drv, vols in by_drv.items():
                for v in vols:
                    ids.append(vol_id("csi:" + drv, v)[0])
        for name, plug in intree:
            out: Set[str] = set()
            plug._count(pod, out)
            for v in out:
                ids.append(vol_id(name, v)[0])
        return ids

    pod_vol_lists = [pod_demands(p) if r else []
                     for p, r in zip(pods, relevant)]
    # one pass over each node's existing pods covers BOTH the limit vol ids
    # and the conflict tokens — this walk is the O(existing pods) cost of
    # the overlay, so it must not run twice
    node_vol_lists: List[List[int]] = []
    node_conf_lists: List[List[int]] = []
    for ni in node_infos:
        ids: List[int] = []
        toks: List[int] = []
        for pi in ni.pods:
            if pi.pod.spec.volumes:
                ids.extend(pod_demands(pi.pod))
                toks.extend(conf_tokens(pi.pod, register=True))
        node_vol_lists.append(sorted(set(ids)))
        node_conf_lists.append(sorted(set(toks)))

    # min-8 floors: tiny per-cycle fluctuations must not walk an XLA
    # recompile ladder on the serving path
    MC = pow2_bucket(max((len(x) for x in pod_conf_lists), default=0), 8)
    MC2 = pow2_bucket(max((len(x) for x in node_conf_lists), default=0), 8)
    pod_conf_ids = np.full((B, MC), -1, np.int32)
    for i, ids in enumerate(pod_conf_lists):
        pod_conf_ids[i, :len(ids)] = ids
    node_conf_ids = np.full((N, MC2), -1, np.int32)
    for n, ids in enumerate(node_conf_lists):
        node_conf_ids[n, :len(ids)] = ids

    V = pow2_bucket(len(vol_ids), 8)
    D = pow2_bucket(len(drivers), 8)
    MV = pow2_bucket(max((len(x) for x in pod_vol_lists), default=0), 8)
    MU = pow2_bucket(max((len(x) for x in node_vol_lists), default=0), 8)
    pod_vol_ids = np.full((B, MV), -1, np.int32)
    for i, ids in enumerate(pod_vol_lists):
        pod_vol_ids[i, :len(ids)] = ids
    node_vol_ids = np.full((N, MU), -1, np.int32)
    for n, ids in enumerate(node_vol_lists):
        node_vol_ids[n, :len(ids)] = ids
    driver_hot = np.zeros((V, D), np.float32)
    for (drv, _), vid in vol_ids.items():
        driver_hot[vid, drivers[drv]] = 1.0

    node_limit = np.full((N, D), _BIG, np.float32)
    for n, ni in enumerate(node_infos):
        if csi is not None:
            for drv, lim in csi._node_limits(ni).items():
                d = drivers.get("csi:" + drv)
                if d is not None:
                    node_limit[n, d] = lim
        for name, plug in intree:
            d = drivers.get(name)
            if d is not None:
                node_limit[n, d] = plug._max_volumes(ni)

    # ---- VolumeBinding: bound PVs + unbound availability per class
    pv_rows: Dict[str, int] = {}
    pv_objs: List[api.PersistentVolume] = []
    # claim-requirement rows: (class, storage request, access modes) ->
    # row, with an exemplar claim per row for the PV-side matcher
    sc_rows: Dict[Tuple, int] = {}
    sc_claims: List[api.PersistentVolumeClaim] = []

    def pv_row(pv) -> int:
        r = pv_rows.get(pv.metadata.name)
        if r is None:
            r = pv_rows[pv.metadata.name] = len(pv_objs)
            pv_objs.append(pv)
        return r

    pod_bound: List[List[int]] = []
    pod_scs: List[List[int]] = []
    pod_all_fail = np.zeros((B,), bool)
    pod_zone_err = np.zeros((B,), bool)
    zone_reqs: List[Optional[api.LabelSelector]] = []
    pod_has_zone = np.zeros((B,), bool)
    for i, (pod, rel) in enumerate(zip(pods, relevant)):
        bound: List[int] = []
        scs: List[int] = []
        # one requirement PER (PV, zone key): the node must satisfy EVERY
        # bound PV's zone set independently — unioning values across PVs
        # would wrongly admit nodes matching only one of them
        zreq: Set[Tuple[str, frozenset]] = set()
        if rel:
            for v in pod.spec.volumes:
                if not v.persistent_volume_claim:
                    continue
                pvc = store.get_pvc(pod.namespace, v.persistent_volume_claim)
                if pvc is None:
                    # VolumeBinding fails every node (and prefilter fails
                    # the pod first); VolumeZone alone only errors on
                    # zone-labeled nodes
                    if binding is not None:
                        pod_all_fail[i] = True
                    elif zone is not None:
                        pod_zone_err[i] = True
                    continue
                if pvc.volume_name:
                    pv = store.get_pv(pvc.volume_name)
                    if pv is None:
                        if binding is not None:
                            pod_all_fail[i] = True
                        elif zone is not None:
                            pod_zone_err[i] = True
                        continue
                    if binding is not None:
                        bound.append(pv_row(pv))
                    if zone is not None:
                        for k, want in pv.metadata.labels.items():
                            if k in vplug._ZONE_KEYS:
                                zreq.add((k, frozenset(want.split("__"))))
                else:
                    sc_name = pvc.storage_class_name
                    sc = (store.get_storage_class(sc_name)
                          if sc_name else None)
                    wffc = (sc is not None and sc.volume_binding_mode
                            == "WaitForFirstConsumer")
                    if zone is not None and not wffc:
                        # VolumeZone errors on unbound claims without a
                        # WaitForFirstConsumer class (volume_zone.go:109)
                        # — on nodes with zone labels
                        pod_zone_err[i] = True
                    if binding is not None and not wffc:
                        # matchable-PV check, keyed by the claim's FULL
                        # requirement signature — class ("" is a real key:
                        # a classless PVC matches classless PVs), storage
                        # request, access modes — so capacity/access-mode
                        # pre-filtering happens host-side at overlay-build
                        # time and the device mask agrees with the host
                        # plugin's commit-time verdict (a permissive mask
                        # here costs a speculative-chain discard per
                        # commit failure in pipelined mode)
                        sig = (sc_name or "",
                               vplug.claim_storage_request(pvc),
                               frozenset(pvc.access_modes))
                        r = sc_rows.get(sig)
                        if r is None:
                            r = sc_rows[sig] = len(sc_rows)
                            sc_claims.append(pvc)
                        scs.append(r)
        pod_bound.append(bound)
        pod_scs.append(scs)
        if zreq:
            pod_has_zone[i] = True
            # AND of per-(PV, key) In requirements == one label selector
            # (repeated keys are fine: requirements AND-combine)
            zone_reqs.append(api.LabelSelector(match_expressions=[
                api.NodeSelectorRequirement(key=k, operator="In",
                                            values=sorted(vals))
                for k, vals in sorted(zreq,
                                      key=lambda kv: (kv[0],
                                                      sorted(kv[1])))]))
        else:
            zone_reqs.append(None)

    # unbound PVs per claim-requirement row (for the matchable check):
    # ONE scan over the PV list probes every registered requirement
    # signature — rows are few (distinct (class, size, modes) triples in
    # the batch), and pv_satisfies_claim is the host plugin's own
    # matcher, so the device verdict can never be more permissive than
    # the commit-time re-check on this dimension
    sc_pv_pairs: List[Tuple[int, int]] = []
    if binding is not None and sc_rows:
        for pv in store.list_pvs():
            if store.pv_is_bound(pv.metadata.name):
                continue
            for sig, r in sc_rows.items():
                if vplug.pv_satisfies_claim(pv, sc_claims[r]):
                    sc_pv_pairs.append((r, pv_row(pv)))

    PVu = pow2_bucket(len(pv_objs), 8)
    # flatten PV nodeAffinity terms (OR-of-terms, like required node
    # affinity); a PV without affinity matches everywhere
    compiler = SelectorCompiler(table)
    term_sels: List = []
    term_of: List[int] = []
    pv_no_aff = np.zeros((PVu,), bool)
    for r, pv in enumerate(pv_objs):
        if pv.node_affinity is None:
            pv_no_aff[r] = True
            continue
        for term in pv.node_affinity.node_selector_terms:
            term_sels.append(term)
            term_of.append(r)
    PVT = pow2_bucket(len(term_sels), 8)
    pv_sel = compiler.compile(term_sels + [None] * (PVT - len(term_sels)),
                              pad_s=PVT, intern_new=False)
    pv_term_of = np.full((PVT,), -1, np.int32)
    pv_term_of[:len(term_of)] = term_of

    pod_pv_hot = np.zeros((B, PVu), np.float32)
    for i, rows in enumerate(pod_bound):
        for r in rows:
            pod_pv_hot[i, r] = 1.0
    SC = pow2_bucket(len(sc_rows), 8)
    sc_pv_hot = np.zeros((SC, PVu), np.float32)
    for r, row in sc_pv_pairs:
        sc_pv_hot[r, row] = 1.0
    pod_sc_hot = np.zeros((B, SC), np.float32)
    for i, rows in enumerate(pod_scs):
        for r in rows:
            pod_sc_hot[i, r] = 1.0

    zone_sel = compiler.compile(zone_reqs + [None] * (B - len(zone_reqs)),
                                pad_s=B, intern_new=False)
    zone_keyids = np.asarray(
        [table.key.get(k) for k in vplug._ZONE_KEYS], np.int32)

    return VolumeOverlay(
        pod_vol_ids=pod_vol_ids, node_vol_ids=node_vol_ids,
        driver_hot=driver_hot, node_limit=node_limit,
        pod_conf_ids=pod_conf_ids, node_conf_ids=node_conf_ids,
        pv_sel=pv_sel, pv_term_of=pv_term_of, pv_no_aff=pv_no_aff,
        pod_pv_hot=pod_pv_hot, sc_pv_hot=sc_pv_hot, pod_sc_hot=pod_sc_hot,
        zone_sel=zone_sel, pod_has_zone=pod_has_zone,
        pod_zone_err=pod_zone_err, zone_keyids=zone_keyids,
        pod_all_fail=pod_all_fail)


def volume_mask(cluster, overlay: VolumeOverlay) -> jnp.ndarray:
    """[B, N] bool feasibility from the volume family, one jitted call.
    Only the node-label tensors enter the jit, so the compile key is
    independent of chained pod-axis bucket growth."""
    return _volume_mask(cluster.kv, cluster.keymask, cluster.num,
                        jax.tree.map(jnp.asarray, overlay))


def _dense(ids: jnp.ndarray, V: int) -> jnp.ndarray:
    X = ids.shape[0]
    rows = jnp.arange(X)[:, None]
    return jnp.zeros((X, V), jnp.float32).at[
        rows, jnp.clip(ids, 0, V - 1)].max(
        ((ids >= 0) & (ids < V)).astype(jnp.float32))


@jax.jit
def _volume_mask(kv, keymask, num, ov: VolumeOverlay) -> jnp.ndarray:
    B = ov.pod_vol_ids.shape[0]
    N = kv.shape[0]

    # ---- VolumeBinding: bound-PV node affinity (OR over terms)
    m = match_selectors(ov.pv_sel, kv, keymask, num)          # [PVT, N]
    PVu = ov.pv_no_aff.shape[0]
    pv_ok = jnp.zeros((PVu, N), jnp.float32).at[
        jnp.clip(ov.pv_term_of, 0, PVu - 1)].max(
        m.astype(jnp.float32) * (ov.pv_term_of >= 0)[:, None])
    pv_ok = jnp.maximum(pv_ok, ov.pv_no_aff[:, None].astype(jnp.float32))
    bound_fail = jnp.einsum("bp,pn->bn", ov.pod_pv_hot, 1.0 - pv_ok,
                            preferred_element_type=jnp.float32) > 0.5
    # unbound claims: every referenced class needs >=1 matchable PV here
    sc_ok = jnp.einsum("sp,pn->sn", ov.sc_pv_hot, pv_ok,
                       preferred_element_type=jnp.float32) > 0.5
    unbound_fail = jnp.einsum("bs,sn->bn", ov.pod_sc_hot,
                              1.0 - sc_ok.astype(jnp.float32),
                              preferred_element_type=jnp.float32) > 0.5

    # ---- VolumeZone
    zid_ok = ov.zone_keyids >= 0
    zk = jnp.clip(ov.zone_keyids, 0, keymask.shape[1] - 1)
    has_any_zone = jnp.any(jnp.take(keymask, zk, axis=1)
                           & zid_ok[None, :], axis=1)          # [N]
    zmatch = match_selectors(ov.zone_sel, kv, keymask, num)[:B]  # [B, N]
    zone_ok = jnp.where(ov.pod_has_zone[:, None],
                        zmatch | ~has_any_zone[None, :], True)
    zone_ok = zone_ok & ~(ov.pod_zone_err[:, None] & has_any_zone[None, :])

    # ---- limits: |used U new| <= limit per driver the pod demands
    V = ov.driver_hot.shape[0]
    D = ov.driver_hot.shape[1]
    pod_vols = _dense(ov.pod_vol_ids, V)      # [B, V]
    node_used = _dense(ov.node_vol_ids, V)    # [N, V]
    ok = jnp.ones((B, N), bool)
    for d in range(D):
        vm = ov.driver_hot[:, d]                               # [V]
        pv_d = pod_vols * vm[None, :]
        extra = jnp.einsum("bv,nv->bn", pv_d, 1.0 - node_used,
                           preferred_element_type=jnp.float32)
        cnt = jnp.einsum("nv,v->n", node_used, vm,
                         preferred_element_type=jnp.float32)
        demand = jnp.sum(pv_d, axis=1) > 0.5
        ok_d = (cnt[None, :] + extra) <= ov.node_limit[:, d][None, :]
        ok = ok & (~demand[:, None] | ok_d)

    # ---- VolumeRestrictions: any shared conflict token fails (MC/MC2 are
    # tiny, so the 4-D equality fuses into the reduce)
    pc, nc = ov.pod_conf_ids, ov.node_conf_ids
    eq = ((pc[:, :, None, None] == nc[None, None, :, :])
          & (pc >= 0)[:, :, None, None])
    conflict = jnp.any(eq, axis=(1, 3))                        # [B, N]

    return (ok & ~bound_fail & ~unbound_fail & zone_ok & ~conflict
            & ~ov.pod_all_fail[:, None])
