"""Zone-aware node tree: nodes grouped by zone, iterated round-robin so
adjacent list positions interleave zones (reference:
pkg/scheduler/internal/cache/node_tree.go:31 nodeTree — the ordering
becomes the node-tensor row permutation in the TPU snapshot)."""

from __future__ import annotations

from typing import Dict, List

from ..api import types as api
from .tensors import zone_key


class NodeTree:
    def __init__(self):
        self._zones: List[str] = []
        self._tree: Dict[str, List[str]] = {}
        self.num_nodes = 0

    def add_node(self, node: api.Node) -> None:
        # reference: node_tree.go:59 addNode
        zone = zone_key(node)
        names = self._tree.get(zone)
        if names is None:
            self._zones.append(zone)
            names = self._tree[zone] = []
        if node.name not in names:
            names.append(node.name)
            self.num_nodes += 1

    def remove_node(self, node: api.Node) -> bool:
        # reference: node_tree.go:87 removeNode
        zone = zone_key(node)
        names = self._tree.get(zone, [])
        if node.name in names:
            names.remove(node.name)
            self.num_nodes -= 1
            if not names:
                del self._tree[zone]
                self._zones.remove(zone)
            return True
        return False

    def update_node(self, old: api.Node, new: api.Node) -> None:
        # reference: node_tree.go:113 updateNode
        if old is not None and zone_key(old) == zone_key(new):
            return
        if old is not None:
            self.remove_node(old)
        self.add_node(new)

    def list(self) -> List[str]:
        """Round-robin over zones (reference: node_tree.go:135 next — the
        iterator state is reset per full listing here since the snapshot
        consumes the whole list)."""
        idx = {z: 0 for z in self._zones}
        out: List[str] = []
        exhausted = 0
        zi = 0
        n_zones = len(self._zones)
        while n_zones and exhausted < n_zones:
            z = self._zones[zi % n_zones]
            i = idx[z]
            if i < len(self._tree[z]):
                out.append(self._tree[z][i])
                idx[z] += 1
                if idx[z] == len(self._tree[z]):
                    exhausted += 1
            zi += 1
        return out
