"""Fake scheduler cache for unit tests.

reference: pkg/scheduler/internal/cache/fake/fake_cache.go — a no-op Cache
whose assume/forget/is-assumed behaviors are injectable hooks, so tests can
observe or script the scheduler's cache interactions without real state.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ..api import types as api
from .cache import Snapshot


class FakeCache:
    """Drop-in for SchedulerCache in tests: every method is a no-op unless
    a hook is injected (assume_fn / forget_fn / is_assumed_fn / get_pod_fn,
    mirroring fake_cache.go's AssumeFunc et al)."""

    def __init__(self,
                 assume_fn: Optional[Callable[[api.Pod], None]] = None,
                 forget_fn: Optional[Callable[[api.Pod], None]] = None,
                 is_assumed_fn: Optional[Callable[[api.Pod], bool]] = None,
                 get_pod_fn: Optional[Callable[[api.Pod],
                                               Optional[api.Pod]]] = None):
        self.assume_fn = assume_fn
        self.forget_fn = forget_fn
        self.is_assumed_fn = is_assumed_fn
        self.get_pod_fn = get_pod_fn
        self.assumed_pods: Dict[str, bool] = {}

    # -- pods ---------------------------------------------------------------

    def assume_pod(self, pod: api.Pod, pinfo=None) -> None:
        if self.assume_fn:
            self.assume_fn(pod)

    def finish_binding(self, pod: api.Pod, now=None) -> None:
        pass

    def forget_pod(self, pod: api.Pod) -> None:
        if self.forget_fn:
            self.forget_fn(pod)

    def add_pod(self, pod: api.Pod) -> None:
        pass

    def update_pod(self, old: api.Pod, new: api.Pod) -> None:
        pass

    def remove_pod(self, pod: api.Pod) -> None:
        pass

    def get_pod(self, pod: api.Pod) -> Optional[api.Pod]:
        return self.get_pod_fn(pod) if self.get_pod_fn else pod

    def is_assumed_pod(self, pod: api.Pod) -> bool:
        return self.is_assumed_fn(pod) if self.is_assumed_fn else False

    # -- nodes / snapshot ---------------------------------------------------

    def add_node(self, node: api.Node) -> None:
        pass

    def update_node(self, old: api.Node, new: api.Node) -> None:
        pass

    def remove_node(self, node: api.Node) -> None:
        pass

    def node_info(self, name: str):
        return None

    def node_fit_view(self, name: str):
        return None

    def node_count(self) -> int:
        return 0

    def pod_count(self) -> int:
        return 0

    def update_snapshot(self, snapshot: Snapshot) -> None:
        pass

    def cleanup_assumed_pods(self, now=None) -> None:
        pass

    def run(self) -> None:
        pass

    def close(self) -> None:
        pass

    def dump(self) -> Dict[str, object]:
        return {"nodes": {}, "assumed_pods": []}
