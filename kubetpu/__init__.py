"""kubetpu: TPU-native batch scheduler.

Importing the package arms the opt-in runtime sanitizer when
``KUBETPU_SANITIZE=1`` (see utils/sanitize.py): jax_debug_nans,
rank-promotion errors, donation-mismatch logging and the per-program
compile-count watchdog.  Off (the default) this import touches nothing
and does not import jax.
"""

from .utils.sanitize import maybe_enable_from_env as _maybe_sanitize

_maybe_sanitize()
