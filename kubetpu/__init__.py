"""kubetpu: TPU-native batch scheduler.

Importing the package arms the opt-in runtime harnesses:

* ``KUBETPU_SANITIZE=1`` (utils/sanitize.py): jax_debug_nans,
  rank-promotion errors, donation-mismatch logging and the per-program
  compile-count watchdog;
* ``KUBETPU_RACE=1`` (utils/racecheck.py): instrumented locks (order +
  hold-time enforcement) and guarded-attribute mutation checks for the
  threaded host path;
* ``KUBETPU_FLIGHT=1`` (utils/trace.py): the cycle flight recorder — a
  ring buffer of the last ``KUBETPU_FLIGHT_N`` scheduling cycles' span
  trees, dumped by ``/debug/flightz`` and exportable as Perfetto/Chrome
  trace-event JSON.

Off (the default) this import touches nothing and does not import jax.
"""

from .utils.racecheck import maybe_enable_from_env as _maybe_racecheck
from .utils.sanitize import maybe_enable_from_env as _maybe_sanitize
from .utils.trace import maybe_arm_from_env as _maybe_flight

_maybe_sanitize()
_maybe_racecheck()
_maybe_flight()
