"""Depth-k pipelined serving executor: overlap prepare(k+1) / device(k) /
commit(k-1).

The serving loop's serial pop -> prepare -> dispatch -> readback ->
commit -> bind chain kept ``host_share`` at 0.5-0.8 across bench cases
("It's the Critical Path!" is the framing; PR 10's ``stage_shares`` name
exactly which stage is exposed).  The old ``Scheduler._schedule_pipelined``
hid SOME of it with a hand-rolled 2-deep chain around a single
``_inflight_cycle`` tuple; this module generalizes that chain into a
bounded ring of dispatched-but-uncommitted ``PreparedCycle``s so that, at
depth k, the host can be tensorizing cycle k+1 while cycle k executes on
device and cycle k-1's commit/bind loop drains — the depth is the lever
that turns measured stage shares into recovered throughput.

``pipelineDepth`` (apis/config.py, env ``KUBETPU_PIPELINE_DEPTH``) is the
maximum number of cycles in flight at once: depth 1 is the fully
synchronous drain (ring capacity 0 — every cycle commits before the next
pops), depth 2 reproduces the old double-buffered chain exactly, depth k
parks up to k-1 dispatched cycles between ``schedule_pending`` calls.
Placements are BIT-IDENTICAL across depths (the parity contract the bench
``pipeline_depth`` case and tests/test_pipeline.py assert): every cycle
dispatches against either the previous cycle's speculative chained
cluster or the committed cache — never a state that can diverge from the
synchronous drain's.

The correctness machinery generalizes from "one uncommitted cycle" to "a
ring of them":

* DONATION WITHHOLDING — ``_prepare_group``'s ``uncommitted=`` is now the
  LIST of every dispatched-but-uncommitted cycle; the DeltaTensorizer's
  donated scatter is withheld while ANY of them still reads the resident
  buffers (``DeltaTensorizer.safe_to_donate``).
* DEADLINE EXEMPTION per ring slot — PR 9's rules (compile activity,
  pipelined commit time, parked think time) apply to every in-flight
  cycle, not just the single ``_inflight_cycle``: commit loops and
  readbacks of OTHER cycles land inside a younger cycle's
  dispatch->readback window and are folded into its ``host_exempt_s``,
  so host work at depth can never demote a healthy device.  The SLO
  layer subtracts the same exemptions from the per-pod ``dispatch``
  stage so overlapped host work is not double-counted across slots.
* CHAIN-BREAK RECOVERY BY SCATTER — when cycle j's readback recovers
  (dispatch error / deadline) or its commit fails, every YOUNGER
  in-flight cycle was dispatched against placements that never
  materialized: each is discarded and re-prepared against a fresh
  snapshot over the pods that survived its first prepare — no pod is
  lost, none binds twice (the already-returned early failures are
  final).

Threading: the executor and its decisions are owned by the serving
thread, like the scheduler's chain; the ring itself is lock-guarded so
``flush_pipeline``/``close`` from the owning thread after a join — and
the kubelint concurrency family — see one consistent container.  No
device dispatch, readback or sleep ever runs under the ring lock.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

from .utils import trace as utrace

PIPELINE_DEPTH_ENV = "KUBETPU_PIPELINE_DEPTH"
DEFAULT_PIPELINE_DEPTH = 2
# the queue's burst-gather window (schedqueue/queue.py pop_batch): pops
# with free pipeline slots may wait this long so an arriving burst lands
# in ONE cycle instead of bucket-churning partial waves
GATHER_WINDOW_S = 0.02


def depth_from_env(default: int) -> int:
    """KUBETPU_PIPELINE_DEPTH overrides the config (an operator can
    re-depth a live fleet); clamped to >= 1."""
    raw = os.environ.get(PIPELINE_DEPTH_ENV)
    try:
        depth = int(raw) if raw else int(default)
    except (TypeError, ValueError):
        depth = int(default) if isinstance(default, int) else \
            DEFAULT_PIPELINE_DEPTH
    return max(depth, 1)


class InflightRing:
    """Bounded ring of dispatched-but-uncommitted cycles, oldest first.

    Each slot holds a ``(PreparedCycle, device result)`` pair between its
    dispatch and its readback+commit.  Capacity = pipeline depth - 1 (the
    cycle being prepared is the +1).  Mutations are lock-guarded; the
    per-slot ``parked_t`` / ``host_exempt_s`` stamps implement the
    per-slot deadline-exemption accounting."""

    def __init__(self, capacity: int):
        self.capacity = max(int(capacity), 0)
        self._lock = threading.Lock()
        self._slots: List[Tuple[object, object]] = []  # kubelint: guarded-by(_lock)
        self.high_water = 0  # kubelint: guarded-by(_lock)

    def __len__(self) -> int:
        with self._lock:
            return len(self._slots)

    def free(self) -> int:
        with self._lock:
            return self.capacity - len(self._slots)

    def append(self, prep, res) -> None:
        with self._lock:
            self._slots.append((prep, res))
            if len(self._slots) > self.high_water:
                self.high_water = len(self._slots)

    def pop_oldest(self):
        with self._lock:
            return self._slots.pop(0) if self._slots else None

    def detach_all(self) -> List[Tuple[object, object]]:
        with self._lock:
            out = list(self._slots)
            self._slots = []
            return out

    def preps(self) -> List[object]:
        with self._lock:
            return [p for p, _ in self._slots]

    def results(self) -> List[object]:
        """Device results of every in-flight slot (the devstats deep
        fence pre-drains them UNTIMED so a sampled cycle's measurement
        never includes older cycles' queued-ahead device work)."""
        with self._lock:
            return [r for _, r in self._slots]

    def park(self, now: float) -> None:
        """Stamp caller think time's start on every in-flight cycle —
        wall clock between ``schedule_pending`` calls is host time and
        must not count against any slot's dispatch deadline."""
        with self._lock:
            for prep, _ in self._slots:
                if not prep.parked_t:
                    prep.parked_t = now

    def unpark(self, now: float) -> None:
        """Fold parked think time into every slot's exemption (the twin
        of ``park``; ``_readback_guarded`` folds any stamp that survives
        to a flush-path readback)."""
        with self._lock:
            for prep, _ in self._slots:
                if prep.parked_t:
                    prep.host_exempt_s += now - prep.parked_t
                    prep.parked_t = 0.0

    def exempt(self, seconds: float) -> None:
        """Host seconds spent on OTHER cycles (an older cycle's commit
        loop or readback) land inside every in-flight slot's
        dispatch->readback window — exempt them all.  Parked slots are
        skipped: their whole window is already accruing via parked_t."""
        if seconds <= 0:
            return
        with self._lock:
            for prep, _ in self._slots:
                if not prep.parked_t:
                    prep.host_exempt_s += seconds


class PipelinedExecutor:
    """The depth-k drain.  Owns the ring; borrows the Scheduler's cycle
    primitives (_prepare_group / _dispatch_group / _readback_guarded /
    _commit_group / _recover_cycle) — the executor is the control flow,
    the scheduler stays the mechanism.  Serving-thread owned."""

    def __init__(self, sched, depth: int):
        self.sched = sched
        self.depth = max(int(depth), 1)
        self.ring = InflightRing(self.depth - 1)
        # discarded-and-re-prepared cycle count (the scatter-recovery
        # telemetry tests and bench read; serving thread only)
        self.reruns = 0

    # ----------------------------------------------------------- introspection

    def inflight_preps(self) -> List[object]:
        """Every dispatched-but-uncommitted PreparedCycle — the donation
        withholding set ``_prepare_group`` consults."""
        return self.ring.preps()

    def inflight_results(self) -> List[object]:
        """Every in-flight slot's device result (see
        InflightRing.results)."""
        return self.ring.results()

    def pop_timeout(self, timeout: Optional[float]) -> Optional[float]:
        """Gate the queue's 20 ms burst-gather window on FREE pipeline
        slots: a full ring pops non-blocking (the oldest cycle's commit
        must not wait behind an arrival window), while a ring with free
        slots allows the gather window so an arriving burst lands in one
        cycle instead of splitting into bucket-churning partial waves —
        at depth > 2 the old "non-blocking whenever any slot is
        occupied" rule would have split every burst.  An empty ring
        blocks the caller's full timeout (nothing in flight to flush);
        explicit non-blocking pops (timeout == 0) never wait."""
        n = len(self.ring)
        if n == 0:
            return timeout
        if self.ring.capacity - n <= 0:
            return 0.0
        if timeout is None:
            return GATHER_WINDOW_S
        return min(timeout, GATHER_WINDOW_S)

    # ----------------------------------------------------------------- drain

    def drain(self, max_batch: int, timeout: float) -> List:
        """One ``schedule_pending`` call's worth of pipelined work: pop,
        prepare (overlapping the ring's device work), commit the oldest
        slot when the ring is full, dispatch, park.  Returns outcomes —
        lagging up to depth-1 cycles; an empty pop flushes one in-flight
        cycle per call and ``[] means no work`` holds once the ring is
        dry."""
        s = self.sched
        ring = self.ring
        returned: List = []
        cycle_start = utrace.wallclock()
        ring.unpark(cycle_start)
        while True:
            qpods = s.queue.pop_batch(max_batch,
                                      timeout=self.pop_timeout(timeout))
            by_profile: Dict[str, List] = {}
            for qp in qpods:
                if s._skip_pod_schedule(qp.pod):
                    continue
                by_profile.setdefault(qp.pod.spec.scheduler_name,
                                      []).append(qp)
            if len(by_profile) != 1:
                # nothing schedulable: commit the OLDEST in-flight cycle
                # (one per call keeps the outcome cadence).  Multi-profile
                # batches flush the whole ring, then fall back to the
                # synchronous path
                if by_profile:
                    outcomes = returned + self.flush()
                    for name, group in by_profile.items():
                        outcomes.extend(s._schedule_group(
                            s.profiles[name], group))
                else:
                    outcomes = returned + self._commit_oldest()
                if s.metrics and outcomes:
                    s.metrics.observe_cycle(len(outcomes),
                                            utrace.wallclock() - cycle_start)
                ring.park(utrace.wallclock())
                return outcomes
            (name, group), = by_profile.items()
            fwk = s.profiles[name]
            # ONE relevance walk per cycle, shared with _prepare_group's
            # host-mask gates (the round-5 ADVICE double-walk finding)
            relevance = s._host_relevance(fwk, group)
            if len(ring) and any(rel for rel, _ in relevance.values()):
                # host filter masks and the volume overlay build from the
                # CACHE, which excludes every uncommitted in-flight
                # cycle's placements — preparing now could pass a node an
                # in-flight cycle just filled.  Commit the whole ring
                # first; volume-less batches (the fast path) keep the
                # full-depth overlap.
                returned += self.flush()
            # prepare k: host tensorize work that overlaps the ring's
            # device execution.  uncommitted=ring: no in-flight cycle's
            # buffers may be donated away before its commit-side device
            # work (preemption wave, decision audit) runs
            prep, early = s._prepare_group(fwk, group,
                                           uncommitted=ring.preps(),
                                           relevance=relevance)
            returned += early
            if prep is None:
                outcomes = returned + self.flush()
                if s.metrics and outcomes:
                    s.metrics.observe_cycle(len(outcomes),
                                            utrace.wallclock() - cycle_start)
                ring.park(utrace.wallclock())
                return outcomes
            if len(ring) and not prep.used_chain:
                # chain break (event landed / vocab overflow / bucket
                # compaction): a fresh rebuild while cycles are
                # uncommitted would miss their placements and could
                # oversubscribe nodes.  Serialize: commit the ring, then
                # re-prepare over the SURVIVING pods only (pods already
                # failed in the first prepare have final outcomes in
                # `early`; re-running _fail would duplicate events)
                returned += self.flush()
                prep, early2 = self._reprepare(prep)
                returned += early2
                if prep is None:
                    ring.park(utrace.wallclock())
                    return returned
            # ring full: readback + commit the oldest slot around k's
            # dispatch.  The readback MUST precede the dispatch (the
            # tunnel serves transfers FIFO behind queued programs)
            oldest = packed_oldest = None
            if len(ring) and ring.free() <= 0:
                oldest = ring.pop_oldest()
                t0 = utrace.wallclock()
                packed_oldest, rec_prev = s._readback_guarded(*oldest)
                ring.exempt(utrace.wallclock() - t0)
                if rec_prev is not None:
                    # the oldest's dispatch errored or blew its deadline:
                    # it was recovered (pods requeued, residents
                    # invalidated) — every younger in-flight cycle AND
                    # the just-prepared k descend from its chain, so all
                    # are discarded and re-run against fresh snapshots
                    returned += rec_prev
                    returned += self._rerun_discarded(ring.detach_all())
                    oldest = packed_oldest = None
                    prep, early2 = self._reprepare(prep)
                    returned += early2
                    if prep is None:
                        ring.park(utrace.wallclock())
                        return returned
            res = None
            with prep.trace.stage(
                    "dispatch",
                    pipelined=oldest is not None or len(ring) > 0):
                try:
                    res = s._dispatch_group(
                        prep,
                        extra_uncommitted=self._uncommitted_pods(oldest))
                except Exception as e:  # device fault at the dispatch
                    # seam: recover k (requeue), still commit the ring
                    returned += s._recover_cycle(prep, repr(e),
                                                 "dispatch-error")
            if res is None:
                prep.trace.finish(recovered="dispatch-error")
                if oldest is not None:
                    outs, _failed = self._commit_entry(oldest[0],
                                                       packed_oldest)
                    returned += outs
                returned += self.flush()
                if s.metrics and returned:
                    s.metrics.observe_cycle(len(returned),
                                            utrace.wallclock() - cycle_start)
                ring.park(utrace.wallclock())
                return returned
            s._last_commit_failed = False
            if oldest is not None:
                # the oldest's commit loop runs on the serving thread
                # while k (and the rest of the ring) execute on device;
                # its wall time is host-exempt for every in-flight slot
                outs, failed = self._commit_entry(oldest[0], packed_oldest,
                                                  exempt_prep=prep)
                returned += outs
                if prep.used_chain and failed:
                    # committing the oldest failed: k (and the younger
                    # ring entries, already re-run by _commit_entry) were
                    # dispatched against placements that never
                    # materialized.  Discard and re-run k synchronously
                    # over the surviving pods only
                    prep, early2 = self._reprepare(prep)
                    returned += early2
                    if prep is None:
                        if s.metrics and returned:
                            s.metrics.observe_cycle(
                                len(returned), utrace.wallclock() - cycle_start)
                        ring.park(utrace.wallclock())
                        return returned
                    with prep.trace.stage("dispatch"):
                        try:
                            res = s._dispatch_group(prep)
                        except Exception as e:
                            returned += s._recover_cycle(
                                prep, repr(e), "dispatch-error")
                            prep.trace.finish(recovered="dispatch-error")
                            if s.metrics and returned:
                                s.metrics.observe_cycle(
                                    len(returned),
                                    utrace.wallclock() - cycle_start)
                            ring.park(utrace.wallclock())
                            return returned
            # ring-slot tag: which pipeline slot this cycle parked in
            # (0 = dispatched straight behind the commit) — traceview
            # renders the slot occupancy so the overlap is visible, and
            # the cycle journal records it on the committed record
            prep.ring_slot = len(ring)
            rec = prep.trace.rec
            if rec is not None:
                rec.meta["ring_slot"] = prep.ring_slot
                rec.meta["pipeline_depth"] = self.depth
            if ring.capacity == 0:
                # depth 1: fully synchronous — the cycle commits before
                # the next pop (no parking, outcomes never lag)
                returned += self._finish_inflight(prep, res)
                if returned:
                    if s.metrics:
                        s.metrics.observe_cycle(len(returned),
                                                utrace.wallclock() - cycle_start)
                    return returned
                continue
            ring.append(prep, res)
            if returned:
                if s.metrics:
                    s.metrics.observe_cycle(len(returned),
                                            utrace.wallclock() - cycle_start)
                ring.park(utrace.wallclock())
                return returned
            # pipe still priming (cycles dispatched, nothing committed
            # yet): loop to pop the next batch so this call still returns
            # outcomes — "[] means no work" stays true for drain loops

    # ----------------------------------------------------------------- flush

    def flush(self) -> List:
        """Commit every in-flight cycle, oldest first (shutdown, chain
        breaks, host-relevant serialization, and callers that need every
        outcome materialized now)."""
        self.ring.unpark(utrace.wallclock())
        outs: List = []
        while len(self.ring):
            outs += self._commit_oldest()
        return outs

    def _commit_oldest(self) -> List:
        """Readback + commit the oldest ring slot (no-op on a dry ring)."""
        entry = self.ring.pop_oldest()
        if entry is None:
            return []
        return self._finish_inflight(*entry)

    def _finish_inflight(self, prep, res) -> List:
        """Readback + commit one detached in-flight cycle.  A pre-commit
        recovery (dispatch error surfacing at the readback, or a blown
        deadline) or a commit failure re-runs every younger in-flight
        cycle by scatter."""
        s = self.sched
        t0 = utrace.wallclock()
        packed, rec = s._readback_guarded(prep, res)
        self.ring.exempt(utrace.wallclock() - t0)
        if packed is None:
            # recovered pre-commit: nothing was reserved or bound; the
            # younger in-flight cycles were built on its chain/residents
            s._last_commit_failed = True
            s._sync_flight_dropped()
            outs = list(rec or [])
            if len(self.ring):
                outs += self._rerun_discarded(self.ring.detach_all())
            return outs
        outs, _failed = self._commit_entry(prep, packed)
        return outs

    def _commit_entry(self, prep, packed, exempt_prep=None) -> Tuple[List, bool]:
        """Commit one already-read-back cycle; its commit-loop wall time
        is exempted for every still-in-flight slot (and exempt_prep, the
        just-dispatched cycle not yet ringed).  Returns (outcomes, THIS
        cycle's commit-failed flag) — a failure re-runs every younger
        ring entry here; the caller handles the un-ringed cycle."""
        s = self.sched
        t0 = utrace.wallclock()
        with prep.trace.stage("commit"):
            outs = s._commit_group(prep, packed)
        failed = s._last_commit_failed
        if s.config.mode == "gang":
            prep.trace.finish(auction_rounds=s.last_gang_rounds,
                              kernel_backend=s._gang_backend(prep))
        else:
            prep.trace.finish()
        dt = utrace.wallclock() - t0
        self.ring.exempt(dt)
        if exempt_prep is not None:
            exempt_prep.host_exempt_s += dt
        s._sync_flight_dropped()
        if failed and len(self.ring):
            outs += self._rerun_discarded(self.ring.detach_all())
        return outs, failed

    # -------------------------------------------------------------- recovery

    def _reprepare(self, prep) -> Tuple[Optional[object], List]:
        """Discard a prepared (possibly dispatched) cycle and prepare it
        again over the pods that SURVIVED the first prepare — pods that
        already failed there have final outcomes and must not fail (and
        emit events / preemption attempts) twice.  Reuses the cycle's
        recorded relevance map, so the host-plugin walk never re-runs."""
        s = self.sched
        stale = prep.trace
        # the discarded cycle may have consumed a journal capture that
        # will now never be journaled — the next journaled cycle must
        # re-anchor (scheduler._journal_note_discard; no-op disarmed)
        s._journal_note_discard(prep)
        new_prep, early = s._prepare_group(prep.fwk, prep.live,
                                           relevance=prep.relevance)
        stale.finish(discarded=True)
        return new_prep, early

    def _rerun_discarded(self, entries: List[Tuple[object, object]]) -> List:
        """Scatter recovery: cycles dispatched against a chain whose
        placements never materialized are discarded and re-run
        SYNCHRONOUSLY, oldest first — each re-prepare sees every commit
        that landed before it (cache state), so no pod is lost and none
        can double-bind.  The rare path; depth resumes on the next pop."""
        s = self.sched
        outs: List = []
        for prep_i, _res in entries:
            self.reruns += 1
            new_prep, early = self._reprepare(prep_i)
            outs += early
            if new_prep is None:
                continue
            with new_prep.trace.stage("dispatch", rerun=True):
                try:
                    res = s._dispatch_group(new_prep)
                except Exception as e:
                    outs += s._recover_cycle(new_prep, repr(e),
                                             "dispatch-error")
                    new_prep.trace.finish(recovered="dispatch-error")
                    continue
            outs += s._finish_group(new_prep, res)
        return outs

    # --------------------------------------------------------------- helpers

    def _uncommitted_pods(self, oldest) -> int:
        """Pods dispatched in earlier cycles whose commits have not
        landed yet — the chain bucket guard's fresh-rebuild estimate
        (includes an oldest slot popped for commit but not committed)."""
        total = sum(int(p.batch.valid.shape[0]) for p in self.ring.preps())
        if oldest is not None:
            total += int(oldest[0].batch.valid.shape[0])
        return total
