"""REST API serving + reflector client: the framework's L2/L3 over HTTP.

Server side (`APIServer`): the ClusterStore behind an HTTP+JSON resource
API — list/get/create/update/delete per kind, the pods/<name>/binding and
pods/<name>/status subresources the scheduler writes (reference:
defaultbinder/default_binder.go:56 POST binding; scheduler.go:739-755
status patch), and a resource-versioned long-poll WATCH feed (the
etcd3-watch + watch-cache role, apiserver/pkg/storage/cacher/cacher.go:436).

Client side (`RestClusterStore`): a ClusterStore whose WRITES go to the
API server and whose READS come from a local mirror maintained by a watch
loop — the Reflector -> DeltaFIFO -> SharedInformer shape of client-go
(tools/cache/reflector.go): initial LIST, then incremental events applied
in order, with subscriber fan-out identical to the in-process store, so a
Scheduler runs against a REMOTE control plane unchanged.
"""

from __future__ import annotations

import collections
import json
import random
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..api import types as api
from ..utils import chaos
from . import codec
from .store import ClusterStore, Conflict, NotFound

WATCH_BUFFER = 16384
# reconnect backoff for the watch loop (reflector.go's wait.Backoff
# shape): exponential from INITIAL, capped, with jitter — a dead or
# flapping API server must cost sleeps, not a spinning core
WATCH_BACKOFF_INITIAL = 0.2
WATCH_BACKOFF_CAP = 5.0


class APIServer:
    """HTTP resource API over a ClusterStore."""

    def __init__(self, store: ClusterStore, host: str = "127.0.0.1",
                 port: int = 0):
        self.store = store
        self.host, self.port = host, port
        self._events = collections.deque(maxlen=WATCH_BUFFER)  # kubelint: guarded-by(_cond)
        self._seq = 0  # kubelint: guarded-by(_cond)
        self._cond = threading.Condition()
        # ThreadingHTTPServer handles writers concurrently, but the store
        # fans events out AFTER releasing its lock — two racing writes
        # could reach the watch buffer in reverse order and make mirrors
        # converge on the older state.  One server-side write mutex makes
        # mutation + event-sequencing atomic per request.
        self._write_lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        for kind in codec.KINDS:
            self._subscribe(kind)

    def _subscribe(self, kind: str) -> None:
        def handler(event, old, new):
            with self._cond:
                self._seq += 1
                self._events.append({
                    "seq": self._seq, "kind": kind, "event": event,
                    "old": codec.to_doc(old) if old is not None else None,
                    "new": codec.to_doc(new) if new is not None else None})
                self._cond.notify_all()
        self.store.subscribe(kind, handler)

    # -- serving ------------------------------------------------------------

    def start(self) -> int:
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code: int, doc) -> None:
                data = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _body(self):
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")

            def do_GET(self):
                try:
                    outer._get(self)
                except Exception as e:  # noqa: BLE001 — API boundary
                    self._send(500, {"error": repr(e)})

            def do_POST(self):
                outer._write(self, "POST")

            def do_PUT(self):
                outer._write(self, "PUT")

            def do_DELETE(self):
                outer._write(self, "DELETE")

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None

    # -- request handling ---------------------------------------------------

    def _get(self, h) -> None:
        path, _, query = h.path.partition("?")
        params = dict(p.split("=", 1) for p in query.split("&") if "=" in p)
        parts = [p for p in path.split("/") if p]
        if parts == ["watch"]:
            since = int(params.get("since", 0))
            timeout = float(params.get("timeout", 25.0))
            with self._cond:
                self._cond.wait_for(
                    lambda: self._seq > since, timeout=timeout)
                evs = [e for e in self._events if e["seq"] > since]
                # oldest retained seq lets clients DETECT buffer eviction
                # (the "resourceVersion too old" signal of a real watch;
                # reflector.go relists on it)
                oldest = self._events[0]["seq"] if self._events else 0
            h._send(200, {"events": evs, "oldest": oldest, "seq": max(
                [e["seq"] for e in evs], default=since)})
            return
        if len(parts) >= 2 and parts[0] == "apis":
            kind = parts[1]
            if kind not in codec.KINDS:
                h._send(404, {"error": f"unknown kind {kind}"})
                return
            if len(parts) == 2:
                # seq is read BEFORE the list: any mutation after the read
                # carries a later seq and will be replayed by the watch
                # (replays are idempotent applies), so the handoff can
                # duplicate but never lose events
                with self._cond:
                    seq0 = self._seq
                h._send(200, {"items": [codec.to_doc(o)
                                        for o in self.store.list(kind)],
                              "seq": seq0})
                return
            key = "/".join(parts[2:])
            obj = self.store.get(kind, key)
            if obj is None:
                h._send(404, {"error": f"{kind} {key} not found"})
                return
            h._send(200, codec.to_doc(obj))
            return
        h._send(404, {"error": "not found"})

    def _write(self, h, method: str) -> None:
        with self._write_lock:
            self._write_locked(h, method)

    def _write_locked(self, h, method: str) -> None:
        try:
            parts = [p for p in h.path.split("/") if p]
            body = h._body() if method != "DELETE" else {}
            # POST /api/v1/namespaces/{ns}/pods/{name}/binding | /status
            # POST .../persistentvolumeclaims/{name}/bind — the PVC-side
            # write of BindPodVolumes (scheduler_binder.go; assume-cache
            # operations stay CLIENT-side like the reference's)
            if (method == "POST" and len(parts) == 7 and parts[0] == "api"
                    and parts[2] == "namespaces"
                    and parts[4] == "persistentvolumeclaims"
                    and parts[6] == "bind"):
                self.store.bind_pvc(parts[3], parts[5],
                                    body.get("pvName", ""),
                                    body.get("nodeName", ""))
                h._send(200, {})
                return
            if (method == "POST" and len(parts) == 7 and parts[0] == "api"
                    and parts[2] == "namespaces" and parts[4] == "pods"):
                ns, name, sub = parts[3], parts[5], parts[6]
                pod = self.store.get_pod(ns, name)
                if pod is None:
                    h._send(404, {"error": f"pod {ns}/{name} not found"})
                    return
                if sub == "binding":
                    self.store.bind(pod, body["node"])
                    h._send(200, {})
                    return
                if sub == "status":
                    cond = codec.from_doc(api.PodCondition,
                                          body.get("condition", {}))
                    self.store.update_pod_condition(
                        pod, cond,
                        nominated_node_name=body.get(
                            "nominatedNodeName", ""))
                    h._send(200, {})
                    return
            if len(parts) >= 2 and parts[0] == "apis":
                kind = parts[1]
                if method == "POST" and len(parts) == 2:
                    self.store.add(codec.decode(kind, body))
                    h._send(201, {})
                    return
                if method == "PUT" and len(parts) >= 3:
                    self.store.update(codec.decode(kind, body))
                    h._send(200, {})
                    return
                if method == "DELETE" and len(parts) >= 3:
                    key = "/".join(parts[2:])
                    obj = self.store.get(kind, key)
                    if obj is None:
                        raise NotFound(f"{kind} {key} not found")
                    self.store.delete(obj)
                    h._send(200, {})
                    return
            h._send(404, {"error": "not found"})
        except Conflict as e:
            h._send(409, {"error": str(e)})
        except NotFound as e:
            h._send(404, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — API boundary
            h._send(500, {"error": repr(e)})


class RestClusterStore(ClusterStore):
    """ClusterStore view of a remote APIServer: reads serve from a local
    watch-maintained mirror; writes POST to the server and become visible
    when their watch event arrives (the reference's informer consistency
    model — the scheduler's assume/ForgetPod protocol bridges the gap,
    cache.go:338)."""

    def __init__(self, base_url: str):
        super().__init__()
        self.base_url = base_url.rstrip("/")
        self._stop = threading.Event()
        self._synced = threading.Event()
        # reconnect accounting (watch thread only): total backoff sleeps
        # taken and the last computed delay — the dead-server test
        # asserts the attempt count stays bounded and the delay grows.
        # The jitter rng is entropy-seeded PER INSTANCE: a shared fixed
        # seed would make every reflector in a fleet draw identical
        # jitter and reconnect in lockstep — the herd the jitter exists
        # to break up
        self._watch_retries = 0
        self._watch_backoff_s = 0.0
        self._backoff_rng = random.Random()
        self._watch_thread = threading.Thread(target=self._watch_loop,
                                              daemon=True)
        self._watch_thread.start()

    # -- transport ----------------------------------------------------------

    def _req(self, method: str, path: str, doc=None, timeout=30.0):
        # chaos seam (utils/chaos.py "rest"): a transient API-server
        # transport error, surfaced exactly where a socket error would be
        chaos.raise_or_stall("rest")
        data = json.dumps(doc).encode() if doc is not None else None
        req = urllib.request.Request(
            self.base_url + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            body = {}
            try:
                body = json.loads(e.read() or b"{}")
            except Exception:  # noqa: BLE001
                pass
            msg = body.get("error", str(e))
            if e.code == 409:
                raise Conflict(msg) from None
            if e.code == 404:
                raise NotFound(msg) from None
            raise

    # -- reflector ----------------------------------------------------------

    def _apply(self, kind: str, event: str, old_doc, new_doc) -> None:
        """Mirror one watch event into the local store, preserving the
        server's resourceVersions, and fan out to subscribers."""
        old = codec.decode(kind, old_doc) if old_doc else None
        new = codec.decode(kind, new_doc) if new_doc else None
        self._apply_obj(kind, event, old, new)

    def _apply_obj(self, kind: str, event: str, old, new) -> None:
        with self._lock:
            if event == "delete":
                self._objs[kind].pop(self._key(old), None)
            else:
                self._objs[kind][self._key(new)] = new
            subs = list(self._subs[kind])
        for h in subs:
            h(event, old, new)

    def _list_all(self) -> Optional[int]:
        """Initial/recovery LIST of every kind (reflector.go ListAndWatch).
        RECONCILES the mirror against the server snapshot: new objects
        emit adds, surviving objects with newer resourceVersions emit
        updates, and local objects absent from the server emit deletes —
        so a relist after a watch gap repairs every divergence, including
        deletions the gap swallowed.  Returns the seq to watch from (the
        MINIMUM of the per-kind list seqs; the handoff window replays
        idempotently) or None if any list failed (caller retries; a
        partial mirror must never be declared synced)."""
        seqs = []
        snapshots = {}
        for kind in codec.KINDS:
            try:
                doc = self._req("GET", f"/apis/{kind}")
            except Exception:  # noqa: BLE001 — transport/server error
                return None
            seqs.append(int(doc.get("seq", 0)))
            snapshots[kind] = doc.get("items", [])
        for kind, items in snapshots.items():
            server = {}
            for item in items:
                obj = codec.decode(kind, item)
                server[self._key(obj)] = obj
            with self._lock:
                local = dict(self._objs[kind])
            for key, obj in server.items():
                old = local.get(key)
                if old is None:
                    self._apply_obj(kind, "add", None, obj)
                elif (old.metadata.resource_version
                        != obj.metadata.resource_version):
                    self._apply_obj(kind, "update", old, obj)
            for key, old in local.items():
                if key not in server:
                    self._apply_obj(kind, "delete", old, None)
        return min(seqs, default=0)

    def _next_backoff(self, failures: int) -> float:
        """Capped exponential backoff with jitter for the reconnect loop
        (reference: reflector.go's wait.Backoff).  failures is the
        CONSECUTIVE failure count; jitter is a uniform [0.5, 1.0) factor
        so a fleet of reflectors does not reconnect in lockstep."""
        self._watch_retries += 1
        base = min(WATCH_BACKOFF_CAP,
                   WATCH_BACKOFF_INITIAL * (2 ** min(failures - 1, 16)))
        delay = base * (0.5 + 0.5 * self._backoff_rng.random())
        self._watch_backoff_s = delay
        return delay

    def _watch_loop(self) -> None:
        seq = None
        failures = 0
        while not self._stop.is_set():
            if seq is None:
                seq = self._list_all()
                if seq is None:
                    failures += 1
                    if self._stop.wait(self._next_backoff(failures)):
                        return
                    continue
                failures = 0
                self._synced.set()
            try:
                # chaos seam (utils/chaos.py "watch"): a dropped watch
                # connection, recovered by the same backoff ladder a real
                # transport error takes
                chaos.raise_or_stall("watch")
                # client bound = server hold (10 s) + slack, so close()'s
                # join bound below really does cover one poll round trip
                doc = self._req("GET", f"/watch?since={seq}&timeout=10",
                                timeout=12.0)
            except Exception:  # noqa: BLE001 — retry after transport error
                failures += 1
                if self._stop.wait(self._next_backoff(failures)):
                    return
                continue
            failures = 0
            # buffer eviction check ("resourceVersion too old"): events
            # older than ours were dropped before we read them -> RELIST
            oldest = int(doc.get("oldest", 0))
            if oldest > seq + 1:
                seq = None
                continue
            try:
                for ev in doc.get("events", []):
                    if ev["seq"] <= seq:
                        continue
                    seq = ev["seq"]
                    self._apply(ev["kind"], ev["event"], ev.get("old"),
                                ev.get("new"))
            except Exception:  # noqa: BLE001 — decode/subscriber failure
                # the loop must never die silently: log and RELIST, which
                # reconciles whatever the failed event left inconsistent
                import logging
                logging.getLogger("kubetpu.rest").warning(
                    "watch event application failed; relisting",
                    exc_info=True)
                seq = None

    def wait_for_cache_sync(self, timeout: float = 10.0) -> bool:
        """reference: WaitForCacheSync before the scheduler serves."""
        return self._synced.wait(timeout)

    def close(self) -> None:
        """Idempotent: stops and joins the watch loop (it long-polls with a
        12 s client timeout, so the join bound covers one poll round
        trip).  If the thread still outlives the bound, the handle is
        KEPT so a later close() can join it again."""
        self._stop.set()
        t = self._watch_thread
        if t is not None and t.is_alive():
            t.join(timeout=15.0)
            if t.is_alive():
                return
        self._watch_thread = None

    # -- writes -> API server ----------------------------------------------

    def add(self, obj) -> None:
        self._req("POST", f"/apis/{obj.kind}", codec.to_doc(obj))

    def update(self, obj) -> None:
        self._req("PUT", f"/apis/{obj.kind}/{self._key(obj)}",
                  codec.to_doc(obj))

    def delete(self, obj) -> None:
        self._req("DELETE", f"/apis/{obj.kind}/{self._key(obj)}")

    def bind(self, pod: api.Pod, node_name: str) -> None:
        self._req("POST",
                  f"/api/v1/namespaces/{pod.namespace}/pods/"
                  f"{pod.metadata.name}/binding", {"node": node_name})

    def update_pod_condition(self, pod, condition,
                             nominated_node_name: str = "") -> None:
        self._req("POST",
                  f"/api/v1/namespaces/{pod.namespace}/pods/"
                  f"{pod.metadata.name}/status",
                  {"condition": codec.to_doc(condition),
                   "nominatedNodeName": nominated_node_name})

    def bind_pvc(self, namespace: str, pvc_name: str, pv_name: str,
                 node_name: str) -> None:
        self._req("POST",
                  f"/api/v1/namespaces/{namespace}/persistentvolumeclaims/"
                  f"{pvc_name}/bind",
                  {"pvName": pv_name, "nodeName": node_name})
        # the local PV assume-cache entry clears the same way the
        # in-process store's does (scheduler_binder assume cache)
        with self._lock:
            if pv_name:
                self._assumed_pv.pop(pv_name, None)
