"""In-process cluster state store: the framework's apiserver + informer.

Plays the role the API server + client-go informer machinery play for the
reference scheduler (reference: staging/src/k8s.io/client-go/tools/cache
{reflector,delta_fifo,shared_informer}.go; the scheduler's view of it is
addAllEventHandlers, pkg/scheduler/eventhandlers.go:362).  Durable state
lives here (etcd's role); device tensors are disposable projections of it
(SURVEY.md §5 checkpoint/resume).

Writes go through typed methods that fan events out to subscribers
synchronously in-process — the integration-test shape of the reference
(test/integration/util/util.go StartApiserver/StartScheduler), which is how
the parity harness runs without a real control plane.  The `bind` method is
the pods/<name>/binding subresource (reference:
defaultbinder/default_binder.go:56, pkg/registry/core/pod BindingREST).
"""

from __future__ import annotations

import copy
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..api import types as api

Handler = Callable[[str, Optional[object], Optional[object]], None]
# handler(event, old, new) with event in {"add", "update", "delete"}

KINDS = ("Pod", "Node", "PersistentVolumeClaim", "PersistentVolume",
         "StorageClass", "CSINode", "Service", "ReplicaSet",
         "ReplicationController", "StatefulSet", "PodDisruptionBudget",
         "Event")


class Conflict(Exception):
    """API write conflict (reference: apierrors.IsConflict paths)."""


class NotFound(Exception):
    pass


class ClusterStore:
    def __init__(self):
        self._lock = threading.RLock()
        self._objs: Dict[str, Dict[str, object]] = {k: {} for k in KINDS}  # kubelint: guarded-by(_lock)
        self._subs: Dict[str, List[Handler]] = {k: [] for k in KINDS}  # kubelint: guarded-by(_lock)
        # PV binding assume-cache (reference: scheduler_binder assume cache)
        self._assumed_pv: Dict[str, str] = {}   # pv name -> pvc name  # kubelint: guarded-by(_lock)

    # -- generic ------------------------------------------------------------

    @staticmethod
    def _key(obj) -> str:
        m = obj.metadata
        return f"{m.namespace}/{m.name}" if getattr(obj, "kind", "") in (
            "Pod", "PersistentVolumeClaim", "Service", "ReplicaSet",
            "ReplicationController", "StatefulSet", "PodDisruptionBudget",
            "Event") \
            else m.name

    def subscribe(self, kind: str, handler: Handler) -> None:
        with self._lock:
            self._subs[kind].append(handler)
            # replay current state as adds (informer initial List)
            current = list(self._objs[kind].values())
        for obj in current:
            handler("add", None, obj)

    def add(self, obj) -> None:
        kind = obj.kind
        with self._lock:
            k = self._key(obj)
            if k in self._objs[kind]:
                raise Conflict(f"{kind} {k} already exists")
            obj.metadata.resource_version += 1
            self._objs[kind][k] = obj
            subs_snapshot = list(self._subs[kind])
        for h in subs_snapshot:
            h("add", None, obj)

    def update(self, obj) -> None:
        kind = obj.kind
        with self._lock:
            k = self._key(obj)
            old = self._objs[kind].get(k)
            if old is None:
                raise NotFound(f"{kind} {k} not found")
            obj.metadata.resource_version = old.metadata.resource_version + 1
            self._objs[kind][k] = obj
            subs_snapshot = list(self._subs[kind])
        for h in subs_snapshot:
            h("update", old, obj)

    def delete(self, obj) -> None:
        kind = obj.kind
        with self._lock:
            k = self._key(obj)
            old = self._objs[kind].pop(k, None)
            if old is None:
                raise NotFound(f"{kind} {k} not found")
            subs_snapshot = list(self._subs[kind])
        for h in subs_snapshot:
            h("delete", old, None)

    def get(self, kind: str, key: str):
        with self._lock:
            return self._objs[kind].get(key)

    def list(self, kind: str) -> List[object]:
        with self._lock:
            return list(self._objs[kind].values())

    # -- typed helpers (what plugins/scheduler use) -------------------------

    def get_pod(self, namespace: str, name: str) -> Optional[api.Pod]:
        return self.get("Pod", f"{namespace}/{name}")

    def get_node(self, name: str) -> Optional[api.Node]:
        return self.get("Node", name)

    def get_pvc(self, namespace: str, name: str) -> Optional[api.PersistentVolumeClaim]:
        return self.get("PersistentVolumeClaim", f"{namespace}/{name}")

    def get_pv(self, name: str) -> Optional[api.PersistentVolume]:
        return self.get("PersistentVolume", name)

    def list_pvs(self) -> List[api.PersistentVolume]:
        return self.list("PersistentVolume")

    def get_storage_class(self, name: str) -> Optional[api.StorageClass]:
        return self.get("StorageClass", name)

    def get_csinode(self, name: str) -> Optional[api.CSINode]:
        return self.get("CSINode", name)

    # -- binding subresource ------------------------------------------------

    def bind(self, pod: api.Pod, node_name: str) -> None:
        """POST pods/<name>/binding (reference: default_binder.go:56).
        Fails if the pod is gone or already bound — the scheduler's
        ForgetPod path handles that (scheduler.go:497)."""
        with self._lock:
            k = f"{pod.namespace}/{pod.metadata.name}"
            current: Optional[api.Pod] = self._objs["Pod"].get(k)
            if current is None:
                raise NotFound(f"pod {k} not found")
            if current.spec.node_name:
                # reference: pkg/registry/core/pod BindingREST rejects any
                # re-bind, even to the same node
                raise Conflict(f"pod {k} is already assigned to node "
                               f"{current.spec.node_name}")
            if self.get("Node", node_name) is None:
                raise NotFound(f"node {node_name} not found")
            old = copy.copy(current)
            old.spec = copy.copy(current.spec)
            current.spec.node_name = node_name
            current.status.phase = api.POD_PENDING
            current.metadata.resource_version += 1
            subs_snapshot = list(self._subs["Pod"])
        for h in subs_snapshot:
            h("update", old, current)

    def update_pod_condition(self, pod: api.Pod, condition: api.PodCondition,
                             nominated_node_name: str = "") -> None:
        """Status patch (reference: scheduler.go:739-755 updatePod)."""
        with self._lock:
            k = f"{pod.namespace}/{pod.metadata.name}"
            current: Optional[api.Pod] = self._objs["Pod"].get(k)
            if current is None:
                raise NotFound(f"pod {k} not found")
            old = copy.copy(current)
            conds = [c for c in current.status.conditions
                     if c.type != condition.type]
            conds.append(condition)
            current.status.conditions = conds
            if nominated_node_name:
                current.status.nominated_node_name = nominated_node_name
            current.metadata.resource_version += 1
            subs_snapshot = list(self._subs["Pod"])
        for h in subs_snapshot:
            h("update", old, current)

    # -- PV binding (SchedulerVolumeBinder surface) -------------------------

    def pv_is_bound(self, pv_name: str) -> bool:
        with self._lock:
            if pv_name in self._assumed_pv:
                return True
            for pvc in self._objs["PersistentVolumeClaim"].values():
                if pvc.volume_name == pv_name:
                    return True
            return False

    def assume_pv_binding(self, pv_name: str, pvc_name: str) -> None:
        with self._lock:
            self._assumed_pv[pv_name] = pvc_name

    def forget_pv_binding(self, pv_name: str) -> None:
        with self._lock:
            self._assumed_pv.pop(pv_name, None)

    def bind_pvc(self, namespace: str, pvc_name: str, pv_name: str,
                 node_name: str) -> None:
        """Write the binding through the 'API' (reference:
        scheduler_binder.go BindPodVolumes -> PVC/PV updates).  Emits a
        PVC update event so watchers (and REST mirrors) see the
        binding."""
        with self._lock:
            pvc = self._objs["PersistentVolumeClaim"].get(f"{namespace}/{pvc_name}")
            if pvc is None:
                raise NotFound(f"pvc {namespace}/{pvc_name} not found")
            old = copy.copy(pvc)
            old.metadata = copy.copy(pvc.metadata)
            if pv_name:
                pvc.volume_name = pv_name
                self._assumed_pv.pop(pv_name, None)
                pvc.phase = "Bound"
            else:
                # delayed provisioning: stamp the selected node and leave the
                # claim Pending for the (external) provisioner (reference:
                # volume.kubernetes.io/selected-node annotation)
                pvc.metadata.annotations = dict(pvc.metadata.annotations)
                pvc.metadata.annotations[
                    "volume.kubernetes.io/selected-node"] = node_name
            pvc.metadata.resource_version += 1
            subs_snapshot = list(self._subs["PersistentVolumeClaim"])
        for h in subs_snapshot:
            h("update", old, pvc)

    # -- spread selectors (DefaultPodTopologySpread) ------------------------

    def default_spread_selector(self, pod: api.Pod):
        """Combined Service/RC/RS/SS selector for the pod (reference:
        defaultpodtopologyspread helpers, plugins/helper/spread.go
        DefaultSelector).  Returns an api.LabelSelector or None."""
        reqs: List[api.LabelSelectorRequirement] = []
        with self._lock:
            for svc in self._objs["Service"].values():
                if svc.metadata.namespace != pod.namespace or not svc.selector:
                    continue
                if all(pod.metadata.labels.get(k) == v
                       for k, v in svc.selector.items()):
                    reqs.extend(api.LabelSelectorRequirement(k, "In", [v])
                                for k, v in svc.selector.items())
            for rc in self._objs["ReplicationController"].values():
                if rc.metadata.namespace != pod.namespace or not rc.selector:
                    continue
                if all(pod.metadata.labels.get(k) == v
                       for k, v in rc.selector.items()):
                    reqs.extend(api.LabelSelectorRequirement(k, "In", [v])
                                for k, v in rc.selector.items())
            for kind in ("ReplicaSet", "StatefulSet"):
                for rs in self._objs[kind].values():
                    if rs.metadata.namespace != pod.namespace:
                        continue
                    if rs.selector is not None and not rs.selector.is_empty() \
                            and rs.selector.matches(pod.metadata.labels):
                        reqs.extend(rs.selector.requirements())
        if not reqs:
            return None
        return api.LabelSelector(match_expressions=reqs)
