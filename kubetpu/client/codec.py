"""Generic dataclass <-> JSON-document codec for the API object model.

The reference serves JSON/protobuf through generated conversion code
(staging/src/k8s.io/api + apimachinery codecs); here the object model is
plain typed dataclasses (kubetpu/api/types.py), so one reflective codec
covers every kind: field types drive decoding, defaults drive omission.
Documents use the dataclass field names verbatim (snake_case) — the wire
format is ours, not Kubernetes', matching SURVEY §1's "minimum L2" scope.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, Optional, get_args, get_origin, get_type_hints

from ..api import types as api

# kinds servable through the REST layer (reference: the scheduler-relevant
# resource registry subset, pkg/registry)
KINDS = {
    "Pod": api.Pod, "Node": api.Node, "Service": api.Service,
    "PersistentVolume": api.PersistentVolume,
    "PersistentVolumeClaim": api.PersistentVolumeClaim,
    "StorageClass": api.StorageClass, "CSINode": api.CSINode,
    "ReplicationController": api.ReplicationController,
    "ReplicaSet": api.ReplicaSet, "StatefulSet": api.StatefulSet,
    "PodDisruptionBudget": api.PodDisruptionBudget,
    "Event": None,  # resolved lazily (utils.events.Event)
}

_hints_cache: Dict[type, Dict[str, Any]] = {}


def _hints(cls) -> Dict[str, Any]:
    h = _hints_cache.get(cls)
    if h is None:
        h = get_type_hints(cls)
        _hints_cache[cls] = h
    return h


def to_doc(obj) -> Any:
    """Dataclass tree -> JSON-able document (None fields omitted)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            v = to_doc(getattr(obj, f.name))
            if v is None:
                continue
            out[f.name] = v
        return out
    if isinstance(obj, (list, tuple)):
        return [to_doc(x) for x in obj]
    if isinstance(obj, dict):
        return {k: to_doc(v) for k, v in obj.items()}
    if isinstance(obj, set):
        return sorted(obj)
    return obj


def from_doc(cls, doc: Any):
    """JSON document -> instance of the (possibly nested) annotated type."""
    if doc is None:
        return None
    origin = get_origin(cls)
    if origin is typing.Union:                    # Optional[T]
        args = [a for a in get_args(cls) if a is not type(None)]
        return from_doc(args[0], doc) if args else doc
    if origin in (list, tuple):
        (item_t, *_rest) = get_args(cls) or (Any,)
        seq = [from_doc(item_t, x) for x in doc]
        return tuple(seq) if origin is tuple else seq
    if origin is set:
        (item_t,) = get_args(cls) or (Any,)
        return {from_doc(item_t, x) for x in doc}
    if origin is dict:
        args = get_args(cls)
        val_t = args[1] if len(args) == 2 else Any
        return {k: from_doc(val_t, v) for k, v in doc.items()}
    if dataclasses.is_dataclass(cls):
        hints = _hints(cls)
        kwargs = {}
        for f in dataclasses.fields(cls):
            if f.name in doc:
                kwargs[f.name] = from_doc(hints.get(f.name, Any), doc[f.name])
        return cls(**kwargs)
    return doc


def decode(kind: str, doc: Dict[str, Any]):
    cls = KINDS.get(kind)
    if cls is None and kind == "Event":
        from ..utils.events import Event
        cls = Event
    if cls is None:
        raise ValueError(f"unservable kind {kind!r}")
    return from_doc(cls, doc)
