"""Executable scheduler: ``python -m kubetpu --config cfg.yaml``.

reference: cmd/kube-scheduler/scheduler.go:1 (main), app/server.go:69-218
(NewSchedulerCommand / Run: config load -> health+metrics serving -> event
broadcasting -> leader election -> scheduler.Run) and app/options/ (the flag
surface).  Standalone runs play the kubemark/hollow tier: ``--hollow-nodes``
populates an in-process store the way hollow kubelets register themselves
(pkg/kubemark/hollow_kubelet.go:35), since this build has no external
apiserver to dial.

Exit codes: 0 clean shutdown; 1 lease lost (server.go:217 — losing the
lease is fatal so a standby takes over); 2 bad flags/config.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
import time
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m kubetpu",
        description="TPU-native scheduler (kube-scheduler parity build)")
    p.add_argument("--config", help="KubeSchedulerConfiguration YAML "
                   "(app/options/configfile.go:40)")
    p.add_argument("--mode", choices=("sequential", "gang"),
                   help="override the device execution mode (sequential = "
                        "bit-parity serial replay; gang = conflict-free "
                        "auction, the throughput mode)")
    p.add_argument("--batch-size", type=int, help="override batch size")
    p.add_argument("--port", type=int, default=0,
                   help="healthz/metrics/configz port (0 = ephemeral; the "
                   "bound port is printed as a JSON line on startup)")
    p.add_argument("--leader-elect", action="store_true",
                   help="enable leader election (overrides config)")
    p.add_argument("--lock-file",
                   help="lease file for cross-process leader election")
    p.add_argument("--lock-identity", help="holder identity (default: pid)")
    p.add_argument("--lease-duration", type=float, default=15.0)
    p.add_argument("--retry-period", type=float, default=2.0)
    p.add_argument("--hollow-nodes", type=int, default=0,
                   help="populate N hollow nodes into the in-process store")
    p.add_argument("--hollow-existing", type=int, default=0,
                   help="pre-bound pods per hollow node")
    p.add_argument("--hollow-pods", type=int, default=0,
                   help="pending hollow pods to enqueue")
    p.add_argument("--once", action="store_true",
                   help="drain the pending queue, print a summary JSON "
                   "line, and exit (the scheduler_perf harness mode)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--drain-timeout", type=float, default=300.0,
                   help="--once: give up draining after this many seconds")
    p.add_argument("--api-port", type=int, default=-1,
                   help="serve the cluster store as a REST resource API "
                        "(list/get/create/delete, pods/binding + status "
                        "subresources, long-poll watch) on this port; 0 "
                        "picks a free port; -1 (default) disables")
    p.add_argument("--api-server",
                   help="connect to a REMOTE kubetpu API server at this "
                        "base URL instead of using an in-process store "
                        "(reflector-fed local cache; writes go over HTTP)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    from .apis.config import (KubeSchedulerConfiguration,
                              KubeSchedulerProfile)
    from .apis.load import ConfigError, load_config_file
    from .client.store import ClusterStore
    from .scheduler import Scheduler
    from .server import SchedulerServer
    from .utils.metrics import SchedulerMetrics

    if args.config:
        try:
            config = load_config_file(args.config)
        except (ConfigError, OSError) as e:
            print(f"error loading --config: {e}", file=sys.stderr)
            return 2
    else:
        config = KubeSchedulerConfiguration(
            profiles=[KubeSchedulerProfile()])
    if args.mode:
        config.mode = args.mode
    if args.batch_size:
        config.batch_size = args.batch_size
    if args.leader_elect:
        config.leader_election = True

    if args.api_server:
        from .client.rest import RestClusterStore
        store = RestClusterStore(args.api_server)
        if not store.wait_for_cache_sync(timeout=30.0):
            # reference: WaitForCacheSync failure is fatal — serving
            # against an unsynced (empty) cache schedules into the void
            print(f"error: could not sync cache from {args.api_server}",
                  file=sys.stderr)
            return 1
    else:
        store = ClusterStore()
    api_srv = None
    if args.api_port >= 0 and not args.api_server:
        from .client.rest import APIServer
        api_srv = APIServer(store, port=args.api_port)
        api_port = api_srv.start()
        print(json.dumps({"kubetpu": "api", "port": api_port}), flush=True)
    metrics = SchedulerMetrics()
    try:
        sched = Scheduler(store, config=config, metrics=metrics,
                          seed=args.seed)
    except ConfigError as e:
        print(f"invalid configuration: {e}", file=sys.stderr)
        return 2

    if args.hollow_nodes or args.hollow_pods:
        from .harness import hollow
        for i, n in enumerate(hollow.make_nodes(args.hollow_nodes, zones=8)):
            store.add(n)
            for p in hollow.make_pods(args.hollow_existing,
                                      prefix=f"ex-{i}-", group_labels=16):
                p.spec.node_name = n.name
                store.add(p)
        for p in hollow.make_pods(args.hollow_pods, prefix="pend-",
                                  group_labels=16):
            store.add(p)

    server = SchedulerServer(sched, port=args.port)
    port = server.start()
    print(json.dumps({"kubetpu": "started", "port": port,
                      "mode": config.mode,
                      "profiles": [pr.scheduler_name
                                   for pr in config.profiles]}), flush=True)

    stop = threading.Event()
    exit_code = [0]

    def on_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    def serve():
        try:
            if args.once:
                # drain: run cycles until no pod is actively retryable —
                # pods parked in unschedulableQ with no cluster event coming
                # are terminal for a --once run
                t0 = time.time()
                deadline = t0 + args.drain_timeout
                outcomes = []
                while not stop.is_set() and time.time() < deadline:
                    sched.queue.flush_backoff_completed()
                    out = sched.schedule_pending(timeout=0.2)
                    outcomes.extend(out)
                    if (not out and len(sched.queue.active_q) == 0
                            and len(sched.queue.backoff_q) == 0):
                        break
                sched.wait_for_inflight_binds()
                bound = sum(1 for o in outcomes if o.node and not o.err)
                print(json.dumps({
                    "scheduled": bound,
                    "attempts": len(outcomes),
                    "unschedulable": len(sched.queue.unschedulable_q),
                    "seconds": round(time.time() - t0, 3),
                }), flush=True)
            else:
                sched.run()
                stop.wait()
        finally:
            stop.set()

    if config.leader_election:
        from .utils.leaderelection import FileLock, InMemoryLock, LeaderElector
        lock = FileLock(args.lock_file) if args.lock_file else InMemoryLock()
        started = threading.Event()

        def on_started():
            started.set()
            threading.Thread(target=serve, daemon=True).start()

        def on_stopped():
            # reference: app/server.go:217 — losing the lease is fatal
            print(json.dumps({"kubetpu": "lease lost, exiting"}),
                  flush=True)
            exit_code[0] = 1
            stop.set()

        import os
        elector = LeaderElector(lock, on_started, on_stopped,
                                identity=args.lock_identity
                                or f"pid-{os.getpid()}",
                                lease_duration=args.lease_duration,
                                retry_period=args.retry_period)
        elector.run(block=False)
        stop.wait()
        elector.release()
    else:
        serve()
        stop.wait()

    sched.close()
    server.stop()
    return exit_code[0]


if __name__ == "__main__":
    sys.exit(main())
